"""qrflow self-tests: call-graph resolution (partials, registry dispatch,
async/thread edges), taint trigger/clean/suppressed fixtures per sink,
ownership-domain race fixtures, SARIF schema validation — and the live
codebase is violation-free (the second CI ratchet, beside qrlint's)."""

from __future__ import annotations

import json
import textwrap
from pathlib import Path

import pytest

from tools.analysis.engine import Engine, FileContext, Project
from tools.analysis.flow import flow_rules
from tools.analysis.flow.callgraph import build_callgraph
from tools.analysis.flow.domains import infer_domains
from tools.analysis.flow.run import main as qrflow_main
from tools.analysis.flow.sarif import check_sarif, to_sarif
from tools.analysis.flow.taint import (DERIVED, PUBLIC, SECRET, TaintEngine,
                                       join, name_taint)

REPO_ROOT = Path(__file__).resolve().parent.parent
PACKAGE = REPO_ROOT / "quantum_resistant_p2p_tpu"


def lint(source: str):
    findings, suppressed = Engine(flow_rules()).lint_source(textwrap.dedent(source))
    return findings, suppressed


def rule_ids(source: str) -> list[str]:
    return sorted(f.rule for f in lint(source)[0])


def _project(source: str, path: str = "mod.py") -> Project:
    ctx = FileContext(path, textwrap.dedent(source))
    return Project({path: ctx})


# -- call graph ---------------------------------------------------------------


def test_callgraph_resolves_partials_with_bound_args():
    cg = build_callgraph(_project(
        """
        import functools

        def log_secret(sk, label):
            pass

        def setup(secret_key):
            handler = functools.partial(log_secret, secret_key)
            return handler
        """
    ))
    partials = [e for e in cg.edges if e.kind == "partial"]
    assert len(partials) == 1
    assert partials[0].callee.name == "log_secret"
    assert partials[0].bound == 1


def test_callgraph_resolves_registry_dispatch(tmp_path):
    """A variable assigned from get_kem(...) dispatches to every class named
    at a register_kem call site — the provider-registry resolution."""
    pkg = tmp_path / "provider"
    pkg.mkdir()
    (pkg / "registry.py").write_text(textwrap.dedent(
        """
        from .impls import JaxKEM, NativeKEM

        def register_kem(name, factory):
            pass

        def get_kem(name):
            pass

        register_kem("A", lambda: JaxKEM())
        register_kem("B", lambda: NativeKEM())
        """
    ))
    (pkg / "impls.py").write_text(textwrap.dedent(
        """
        class JaxKEM:
            def decapsulate(self, sk, ct):
                return b""

        class NativeKEM:
            def decapsulate(self, sk, ct):
                return b""
        """
    ))
    (pkg / "app.py").write_text(textwrap.dedent(
        """
        from .registry import get_kem

        def use(sk, ct):
            kem = get_kem("A")
            return kem.decapsulate(sk, ct)
        """
    ))
    contexts = {str(p): FileContext(str(p), p.read_text())
                for p in sorted(pkg.glob("*.py"))}
    cg = build_callgraph(Project(contexts))
    callees = {e.callee.qualname for e in cg.edges
               if e.caller.name == "use" and e.callee.name == "decapsulate"}
    assert callees == {"JaxKEM.decapsulate", "NativeKEM.decapsulate"}


def test_callgraph_marks_async_thread_and_callback_edges():
    cg = build_callgraph(_project(
        """
        import asyncio
        import threading

        class S:
            async def caller(self):
                await self.helper()
                fut = asyncio.get_event_loop().run_in_executor(None, self.blocking)
                fut.add_done_callback(self.done)

            async def helper(self):
                pass

            def start(self):
                threading.Thread(target=self.bg, name="warm").start()

            def bg(self):
                pass

            def blocking(self):
                pass

            def done(self, f):
                pass
        """
    ))
    kinds = {(e.callee.name, e.kind) for e in cg.edges}
    assert ("helper", "await") in kinds
    assert ("bg", "thread") in kinds
    assert ("blocking", "executor") in kinds
    assert ("done", "loop_cb") in kinds
    thread_edge = next(e for e in cg.edges if e.kind == "thread")
    assert thread_edge.label == "thread:warm"


def test_domains_propagate_through_sync_helpers():
    project = _project(
        """
        import threading

        class S:
            def start(self):
                threading.Thread(target=self._bg, name="w").start()

            def _bg(self):
                self._shared_helper()

            async def _serve(self):
                self._shared_helper()

            def _shared_helper(self):
                pass
        """
    )
    cg = build_callgraph(project)
    domains = infer_domains(cg)
    helper = next(f for f in cg.functions.values() if f.name == "_shared_helper")
    assert domains[helper.fid] == {"loop", "thread:w"}


def test_callgraph_subprocess_edge_resolves_worker_main():
    """A ``create_subprocess_exec(sys.executable, "-m", <module>, cfg)``
    spawn (the fleet gateway spawn, fleet/manager.py) resolves to that
    module's ``main`` as a ``subprocess`` ownership edge: the worker runs
    in its OWN process (it can never race the manager) but stays
    reachable/attributed for the dead-code and ownership views."""
    project = Project({
        "pkg/manager.py": FileContext("pkg/manager.py", textwrap.dedent(
            """
            import asyncio
            import sys

            async def spawn(cfg):
                await asyncio.create_subprocess_exec(
                    sys.executable, "-m", "pkg.gateway", cfg)
            """)),
        "pkg/gateway.py": FileContext("pkg/gateway.py", textwrap.dedent(
            """
            def main(argv=None):
                return 0
            """)),
    })
    cg = build_callgraph(project)
    edge = next(e for e in cg.edges if e.kind == "subprocess")
    assert edge.callee.name == "main"
    assert edge.callee.fid.startswith("pkg/gateway.py")
    domains = infer_domains(cg)
    assert domains[edge.callee.fid] == {"subprocess"}


def test_callgraph_on_event_registration_is_a_loop_cb_edge():
    """Fleet ``on_event`` handler registrations fire from the control read
    loops / health tick — loop-domain callbacks, modeled exactly like a
    call_soon registration."""
    cg = build_callgraph(_project(
        """
        def watch(fleet):
            fleet.on_event(note)

        def note(event, gateway):
            pass
        """
    ))
    assert any(e.kind == "loop_cb" and e.callee.name == "note"
               for e in cg.edges)
    domains = infer_domains(cg)
    note = next(f for f in cg.functions.values() if f.name == "note")
    assert "loop" in domains[note.fid]


# -- taint lattice mechanics --------------------------------------------------


def test_lattice_join_and_tuple_models():
    s, p = name_taint("secret_key"), name_taint("public_key")
    assert s.level == SECRET and p.level == PUBLIC
    assert join(s, p).level == SECRET
    kp = name_taint("sig_keypair")
    assert kp.elements is not None
    assert kp.elements[0].level == PUBLIC and kp.elements[1].level == SECRET
    assert name_taint("secret_key_len").level == PUBLIC  # metadata, not secret


def test_interprocedural_summary_returns_secret():
    """decapsulate() -> helper return -> caller local -> logging sink: three
    frames, no secret-looking names along the way."""
    ids = rule_ids(
        """
        import logging
        logger = logging.getLogger(__name__)

        def unwrap(kem, a, b):
            return kem.decapsulate(a, b)

        def middle(kem, a, b):
            return unwrap(kem, a, b)

        def handle(kem, a, b):
            out = middle(kem, a, b)
            logger.info("done %s", out)
        """
    )
    assert ids == ["flow-secret-in-log"]


def test_signature_and_ciphertext_models_stay_public():
    """sign()/encrypt() consume secret keys but their outputs are public by
    construction: no finding when they go to the wire."""
    ids = rule_ids(
        """
        def respond(node, sig_algo, aead, sk, key, msg):
            sig = sig_algo.sign(sk, msg)
            ct = aead.encrypt(key, msg, b"ad")
            node.send_message("peer", "msg", sig=sig, ct=ct)
        """
    )
    assert ids == []


def test_keypair_tuple_public_half_is_sendable():
    ids = rule_ids(
        """
        def announce(node, kem):
            pk, sk = kem.generate_keypair()
            node.send_message("peer", "hello", pk=pk.hex())
        """
    )
    assert ids == []
    ids = rule_ids(
        """
        def leak(node, kem):
            pk, sk = kem.generate_keypair()
            node.send_message("peer", "oops", sk=sk.hex())
        """
    )
    assert ids == ["flow-secret-to-network"]


# -- per-sink trigger / clean / suppressed fixtures ---------------------------


def test_sink_exception_trigger_clean_suppressed():
    assert rule_ids(
        """
        def f(kem, a, b):
            ss = kem.decapsulate(a, b)
            raise ValueError(ss)
        """
    ) == ["flow-secret-in-exception"]
    assert rule_ids(
        """
        def f(kem, a, b):
            ss = kem.decapsulate(a, b)
            raise ValueError(len(ss))
        """
    ) == []
    findings, suppressed = lint(
        """
        def f(kem, a, b):
            ss = kem.decapsulate(a, b)
            raise ValueError(ss)  # qrlint: disable=flow-secret-in-exception — KAT harness: ss is a fixed test vector
        """
    )
    assert not findings
    assert [s.rule for s in suppressed] == ["flow-secret-in-exception"]


def test_sink_binary_frame_trigger_clean_suppressed():
    """flow-secret-to-network over the negotiated binary wire: the
    ``_send_frame_bin`` encode chokepoint (net/p2p_node.py) is a raw-bytes
    network sink — key material in a binary field leaves the process
    verbatim, with no b64/hex step to catch it."""
    assert rule_ids(
        """
        async def leak(node, peer, kem, sk, ct):
            ss = kem.decapsulate(sk, ct)
            await node._send_frame_bin(peer.writer, peer.write_lock,
                                       {"type": "oops", "ct": ss})
        """
    ) == ["flow-secret-to-network"]
    # clean: AEAD output is public by construction — the normal data path
    assert rule_ids(
        """
        async def send(node, peer, aead, key, msg, ad):
            ct = aead.encrypt(key, msg, ad)
            await node._send_frame_bin(peer.writer, peer.write_lock,
                                       {"type": "secure_message", "ct": ct})
        """
    ) == []
    findings, suppressed = lint(
        """
        async def probe(node, peer, kem, sk, ct):
            ss = kem.decapsulate(sk, ct)
            await node._send_frame_bin(peer.writer, peer.write_lock, {"type": "kat", "ss": ss})  # qrlint: disable=flow-secret-to-network — KAT harness: ss is a pinned test vector sent to a loopback checker
        """
    )
    assert not findings
    assert [s.rule for s in suppressed] == ["flow-secret-to-network"]


def test_deterministic_seal_open_models_stay_public():
    """seal()/open_() (the deterministic-nonce AEAD primitives) are
    modeled like encrypt()/decrypt(): outputs public, so the batched
    facade's fallback path stays violation-free."""
    assert rule_ids(
        """
        def f(node, scalar, key, nonce, msg):
            ct = scalar.seal(key, nonce, msg, b"ad")
            node.send_message("peer", "m", ct=ct)
        """
    ) == []
    assert rule_ids(
        """
        def f(node, scalar, key, nonce, blob):
            pt = scalar.open_(key, nonce, blob, b"ad")
            return f"pt={pt!r}"
        """
    ) == []


def test_resumption_ticket_models():
    """Session-resumption models (app/resumption.py): the STEK and the
    resumption master secret are SECRET sources; the STEK-sealed blob is
    public BY CONSTRUCTION (like sign/encrypt outputs), and open_ticket's
    tuple keeps the metadata branchable while the secret stays hot."""
    # trigger: the derived resumption secret reaching a logging sink
    assert rule_ids(
        """
        import logging
        logger = logging.getLogger(__name__)

        def mint(raw, a, b):
            rsec = derive_resumption_secret(raw, a, b)
            logger.info("minting %s", rsec)
        """
    ) == ["flow-secret-in-log"]
    # trigger: a stek-named key is a SECRET source wherever it goes
    assert rule_ids(
        """
        def push(node, stek_key):
            node.send_message("peer", "keys", k=stek_key)
        """
    ) == ["flow-secret-to-network"]
    # clean: the SEALED blob is public by construction — minting a ticket
    # from the secret and sending the blob raises nothing
    assert rule_ids(
        """
        def mint_and_send(node, ring, raw, a, b):
            rsec = derive_resumption_secret(raw, a, b)
            blob = ring.seal_ticket({"secret": rsec.hex()})
            node.send_message("peer", "ke_response", ticket=blob)
        """
    ) == []
    # clean: open_ticket's tuple separates branchable metadata from the
    # SECRET second element; deriving the resumed key is fine...
    assert rule_ids(
        """
        def respond(ring, blob, aead):
            fields, rsec = ring.open_ticket(blob)
            if fields["expires_at"] < 0:
                return None
            return derive_resumed_key(rsec, "c", "s", aead)
        """
    ) == []
    # ...but logging the secret element is the violation
    assert rule_ids(
        """
        import logging
        logger = logging.getLogger(__name__)

        def respond(ring, blob):
            fields, rsec = ring.open_ticket(blob)
            logger.info("resume %s", rsec)
        """
    ) == ["flow-secret-in-log"]


def test_resumption_model_suppression_policed():
    findings, suppressed = lint(
        """
        import logging
        logger = logging.getLogger(__name__)

        def debug_mint(raw, a, b):
            rsec = derive_resumption_secret(raw, a, b)
            logger.debug("rsec %s", rsec)  # qrlint: disable=flow-secret-in-log — fixture: justified debug tap in a test harness
        """
    )
    assert not findings
    assert [s.rule for s in suppressed] == ["flow-secret-in-log"]


def test_sink_format_trigger_and_clean():
    assert rule_ids(
        """
        def f(secret_key):
            return f"sk={secret_key.hex()}"
        """
    ) == ["flow-secret-format"]
    assert rule_ids(
        """
        def f(secret_key):
            return f"sk is {len(secret_key)} bytes"
        """
    ) == []


def test_sink_compare_trigger_clean_and_mask_exemptions():
    assert rule_ids(
        """
        def check(kem, sk, ct, expected):
            if kem.decapsulate(sk, ct) != expected:
                return False
            return True
        """
    ) == ["flow-secret-compare"]
    assert rule_ids(
        """
        import hmac

        def check(kem, sk, ct, expected):
            if not hmac.compare_digest(kem.decapsulate(sk, ct), expected):
                return False
            return True
        """
    ) == []
    # expression-position == on arrays is vectorized masking (FO re-encrypt
    # checks), not a Python branch: constant-time by construction
    assert rule_ids(
        """
        import jax.numpy as jnp

        def fo_check(secret_val, idx, ml, c, c2, key2, key_bar):
            onehot = (jnp.arange(16) == secret_val).astype(jnp.int32)
            ok = jnp.all(c == secret_val, axis=-1)
            return jnp.where(ok, key2, key_bar), onehot
        """
    ) == []


def test_sink_trace_trigger_clean_suppressed():
    """flow-secret-in-trace: span attributes, metric labels, and flight
    payloads are secret sinks (obs/ exports them in cleartext diagnostics)."""
    assert rule_ids(
        """
        def f(tracer, kem, a, b):
            ss = kem.decapsulate(a, b)
            with tracer.span("op", material=ss):
                pass
        """
    ) == ["flow-secret-in-trace"]
    # metadata about the secret is fine (len() sanitizes)
    assert rule_ids(
        """
        def f(tracer, kem, a, b):
            ss = kem.decapsulate(a, b)
            with tracer.span("op", n=len(ss)):
                pass
        """
    ) == []
    # flight-recorder payloads are sinks (receiver hint: flight/recorder)
    assert rule_ids(
        """
        def f(flight, secret_key):
            flight.record("ev", material=secret_key)
        """
    ) == ["flow-secret-in-trace"]
    # metric label values are sinks unconditionally
    assert rule_ids(
        """
        def g(counter, secret_key):
            counter.labels(peer=secret_key).inc()
        """
    ) == ["flow-secret-in-trace"]
    # an unrelated record() receiver stays quiet even with a secret nearby
    assert rule_ids(
        """
        def h(window, secret_key):
            window.record(len(secret_key))
        """
    ) == []
    findings, suppressed = lint(
        """
        def f(flight, kem, a, b):
            ss = kem.decapsulate(a, b)
            flight.record("probe", digest=ss)  # qrlint: disable=flow-secret-in-trace — fixture: pinned KAT vector, not live key material
        """
    )
    assert not findings
    assert [s.rule for s in suppressed] == ["flow-secret-in-trace"]


def test_sink_wire_propagation_trigger_clean_suppressed():
    """flow-secret-in-trace over the cross-peer propagation surface
    (obs/trace.py wire_context/adopt_wire_context): whatever reaches these
    functions rides the network in the ``_trace`` frame field, so only
    correlation ids may ever flow in."""
    assert rule_ids(
        """
        def f(obs_trace, kem, a, b, msg):
            ss = kem.decapsulate(a, b)
            msg["_trace"] = obs_trace.wire_context(session=ss)
        """
    ) == ["flow-secret-in-trace"]
    # the adopt side is the same surface (a tainted value fed to the
    # validator would still transit taint into correlation state)
    assert rule_ids(
        """
        def g(obs_trace, secret_key):
            return obs_trace.adopt_wire_context(secret_key)
        """
    ) == ["flow-secret-in-trace"]
    # the shipped shape: ids-only attachment, public inbound field
    assert rule_ids(
        """
        def f(obs_trace, msg, message):
            ctx = obs_trace.wire_context()
            if ctx is not None:
                msg["_trace"] = ctx
            parent = obs_trace.adopt_wire_context(message.pop("_trace", None))
            return parent
        """
    ) == []
    findings, suppressed = lint(
        """
        def f(obs_trace, kem, a, b, msg):
            ss = kem.decapsulate(a, b)
            msg["_trace"] = obs_trace.wire_context(tag=ss)  # qrlint: disable=flow-secret-in-trace — fixture: pinned KAT digest used as a run tag, not live key material
        """
    )
    assert not findings
    assert [s.rule for s in suppressed] == ["flow-secret-in-trace"]


def test_sink_http_respond_trigger_clean_suppressed():
    """flow-secret-to-network over the HTTP telemetry surface
    (obs/http.py): ``_respond`` is the single response-write chokepoint —
    whatever reaches it is served to whoever scrapes the endpoint, so
    bodies may be built only from registry snapshots / SLO reports /
    span dumps, never key material."""
    assert rule_ids(
        """
        def do_get(handler, kem, a, b):
            ss = kem.decapsulate(a, b)
            handler._respond(200, "application/json", ss)
        """
    ) == ["flow-secret-to-network"]
    # the shipped shape: a registry snapshot is public by construction
    assert rule_ids(
        """
        def do_get(handler, registry, json):
            body = json.dumps(registry.snapshot()).encode()
            handler._respond(200, "application/json", body)
        """
    ) == []
    # metadata about a secret stays clean (len() sanitizes)
    assert rule_ids(
        """
        def do_get(handler, secret_key, json):
            body = json.dumps({"n": len(secret_key)}).encode()
            handler._respond(200, "application/json", body)
        """
    ) == []
    findings, suppressed = lint(
        """
        def do_get(handler, kem, a, b):
            ss = kem.decapsulate(a, b)
            handler._respond(200, "application/json", ss)  # qrlint: disable=flow-secret-to-network — fixture: pinned KAT digest served to a loopback test scraper
        """
    )
    assert not findings
    assert [s.rule for s in suppressed] == ["flow-secret-to-network"]


def test_sink_branch_trigger_and_clean():
    ids = rule_ids(
        """
        def f(table, secret_key):
            if secret_key[0] > 5:
                return table[secret_key[1]]
            return None
        """
    )
    assert ids == ["flow-secret-branch", "flow-secret-branch"]
    # presence checks and truthiness reveal existence, not content
    assert rule_ids(
        """
        def f(secrets_map, peer):
            secret = secrets_map.pop(peer, None)
            if secret is not None:
                return True
            if not secret:
                return False
        """
    ) == []


def test_zeroized_secret_is_no_longer_a_finding():
    assert rule_ids(
        """
        import logging
        logger = logging.getLogger(__name__)

        def f(kem, a, b):
            ss = kem.decapsulate(a, b)
            ss = b""
            logger.info("state %s", ss)
        """
    ) == []


def test_wipe_call_zeroizes():
    assert rule_ids(
        """
        import logging
        logger = logging.getLogger(__name__)

        def wipe(buf):
            pass

        def f(kem, a, b):
            ss = kem.decapsulate(a, b)
            wipe(ss)
            logger.info("state %s", ss)
        """
    ) == []


def test_hkdf_output_is_derived_and_logged_fires():
    ids = rule_ids(
        """
        import logging
        logger = logging.getLogger(__name__)

        def rekey(secret, a, b):
            key = derive_message_key(secret, a, b, "AES")
            logger.debug("new key %s", key)
        """
    )
    assert ids == ["flow-secret-in-log"]


# -- race pack ----------------------------------------------------------------


RACE_SRC = """
    import asyncio
    import threading

    class Stats:
        def __init__(self):
            self.count = 0
            self.guarded = 0
            self._lock = threading.Lock()

        def bump(self):
            self.count += 1

        def bump_guarded(self):
            with self._lock:
                self.guarded += 1

    class Service:
        def __init__(self):
            self.stats = Stats()

        def start(self):
            threading.Thread(target=self._warm, name="warm").start()

        def _warm(self):
            self.stats.bump()
            self.stats.bump_guarded()

        async def serve(self):
            self.stats.bump()
            self.stats.bump_guarded()
    """


def test_cross_thread_state_trigger_and_lock_clean():
    findings, _ = lint(RACE_SRC)
    assert [f.rule for f in findings] == ["cross-thread-state"]
    assert "Stats.count" in findings[0].message
    assert "thread:warm" in findings[0].message


def test_cross_thread_state_suppressed():
    findings, suppressed = lint(RACE_SRC.replace(
        "            self.count += 1",
        "            self.count += 1  # qrlint: disable=cross-thread-state — counter is advisory; losing an increment is acceptable",
    ))
    assert not findings
    assert [s.rule for s in suppressed] == ["cross-thread-state"]


PLACED_SRC = """
    import threading

    class Queue:
        def __init__(self):
            self.served = 0
            self._lock = threading.Lock()

        def _work(self, items):
            self.served += len(items)
            return items

        async def flush(self, shard, items):
            # the sharded crypto plane's placement boundary: _work runs on
            # a dispatch worker under the shard's placement context
            return shard.run_placed(self._work, items)

        async def account(self, items):
            with self._lock:
                self.served += len(items)
    """


def test_placement_call_is_a_cross_thread_edge():
    """qrflow's domain map covers the scheduler surface: a callable handed
    to ``run_placed`` acquires the executor domain, so unlocked state it
    shares with the loop is a race — exactly like a pool submission."""
    findings, _ = lint(PLACED_SRC)
    assert [f.rule for f in findings] == ["cross-thread-state"]
    assert "Queue.served" in findings[0].message
    assert "executor" in findings[0].message


def test_placement_edge_lock_guarded_is_clean():
    clean = PLACED_SRC.replace(
        "        def _work(self, items):\n"
        "            self.served += len(items)\n",
        "        def _work(self, items):\n"
        "            with self._lock:\n"
        "                self.served += len(items)\n",
    )
    assert "cross-thread-state" not in rule_ids(clean)


def test_placement_edge_suppressed():
    findings, suppressed = lint(PLACED_SRC.replace(
        "            self.served += len(items)\n            return items",
        "            self.served += len(items)  # qrlint: disable=cross-thread-state — advisory load counter; a lost increment is acceptable\n            return items",
    ))
    assert "cross-thread-state" not in {f.rule for f in findings}
    assert "cross-thread-state" in {s.rule for s in suppressed}


GAUGE_SRC = """
    import threading

    class Tuner:
        def __init__(self, registry):
            self.bucket = 1
            self.window = 0.5
            self._lock = threading.Lock()
            registry.gauge("bucket").set_fn(self._read_bucket)

        def _read_bucket(self):
            # scrape-side callback: runs on whatever thread snapshots
            self.bucket = max(1, self.bucket)
            return self.bucket

        async def step(self):
            self.bucket = self.bucket * 2
            with self._lock:
                self.window = 0.001
    """


def test_gauge_set_fn_callback_is_a_cross_thread_edge():
    """The autotuner surface (ISSUE 8): a callable handed to a gauge's
    ``set_fn`` runs at snapshot/scrape/flight-dump time on whatever thread
    asks — the domains map treats it as executor-owned, so unlocked tuner
    state it shares with the loop-side stepper is a race."""
    findings, _ = lint(GAUGE_SRC)
    assert [f.rule for f in findings] == ["cross-thread-state"]
    assert "Tuner.bucket" in findings[0].message
    assert "executor" in findings[0].message


def test_gauge_set_fn_lock_guarded_is_clean():
    clean = GAUGE_SRC.replace(
        "            self.bucket = max(1, self.bucket)\n"
        "            return self.bucket",
        "            with self._lock:\n"
        "                self.bucket = max(1, self.bucket)\n"
        "                return self.bucket",
    ).replace(
        "            self.bucket = self.bucket * 2\n",
        "            with self._lock:\n"
        "                self.bucket = self.bucket * 2\n",
    )
    assert "cross-thread-state" not in rule_ids(clean)


def test_gauge_set_fn_edge_suppressed():
    findings, suppressed = lint(GAUGE_SRC.replace(
        "            self.bucket = max(1, self.bucket)",
        "            self.bucket = max(1, self.bucket)  # qrlint: disable=cross-thread-state — scrape-side clamp of an int is advisory; torn reads acceptable",
    ).replace(
        "            self.bucket = self.bucket * 2",
        "            self.bucket = self.bucket * 2  # qrlint: disable=cross-thread-state — scrape-side clamp of an int is advisory; torn reads acceptable",
    ))
    assert "cross-thread-state" not in {f.rule for f in findings}
    assert "cross-thread-state" in {s.rule for s in suppressed}


def test_init_writes_are_construction_not_sharing():
    assert rule_ids(
        """
        import threading

        class S:
            def __init__(self):
                self.flag = False
                threading.Thread(target=self._bg).start()

            def _bg(self):
                pass
        """
    ) == []


def test_asyncio_off_loop_trigger_and_threadsafe_clean():
    src = """
        import threading

        class S:
            def start(self):
                threading.Thread(target=self._bg).start()

            def _bg(self):
                self.loop.{call}

            async def _work(self):
                pass
        """
    assert rule_ids(src.format(call="create_task(self._work())")) == [
        "asyncio-off-loop"]
    assert rule_ids(src.format(call="call_soon_threadsafe(print)")) == []


# -- suppression-justification ratchet ---------------------------------------


def test_unjustified_suppression_fires_and_justified_passes():
    bad = """
        def f(kem, a, b):
            ss = kem.decapsulate(a, b)
            raise ValueError(ss)  # qrlint: disable=flow-secret-in-exception
        """
    ids = rule_ids(bad)
    assert ids == ["unjustified-suppression"]
    good = bad.replace(
        "disable=flow-secret-in-exception",
        "disable=flow-secret-in-exception — fixture: fixed test vector")
    assert rule_ids(good) == []


def test_qrlint_rule_suppressions_are_not_policed():
    # qrflow only enforces justifications for its OWN ids
    assert rule_ids(
        """
        def f(g):
            try:
                g()
            except Exception:  # qrlint: disable=broad-except
                pass
        """
    ) == []


# -- output formats -----------------------------------------------------------


def test_sarif_output_passes_schema_check(tmp_path, capsys):
    bad = tmp_path / "bad.py"
    bad.write_text(textwrap.dedent(
        """
        import logging
        logger = logging.getLogger(__name__)

        def f(kem, a, b):
            ss = kem.decapsulate(a, b)
            logger.info("%s", ss)

        def g(kem, a, b):
            ss = kem.decapsulate(a, b)
            return repr(ss)  # qrlint: disable=flow-secret-format — fixture: suppressed on purpose
        """
    ))
    rc = qrflow_main([str(bad), "--format", "sarif"])
    assert rc == 1
    doc = json.loads(capsys.readouterr().out)
    assert check_sarif(doc) == []
    run = doc["runs"][0]
    assert run["tool"]["driver"]["name"] == "qrflow"
    live = [r for r in run["results"] if "suppressions" not in r]
    waived = [r for r in run["results"] if "suppressions" in r]
    assert [r["ruleId"] for r in live] == ["flow-secret-in-log"]
    assert [r["ruleId"] for r in waived] == ["flow-secret-format"]
    region = live[0]["locations"][0]["physicalLocation"]["region"]
    assert region["startLine"] >= 1 and region["startColumn"] >= 1


def test_sarif_schema_checker_rejects_malformed():
    assert check_sarif({"version": "2.1.0"})          # missing runs
    assert check_sarif({"version": "1.0", "runs": []})  # wrong version
    ok = to_sarif([], [], flow_rules())
    assert check_sarif(ok) == []


def test_cli_json_select_and_exit_codes(tmp_path, capsys):
    bad = tmp_path / "bad.py"
    bad.write_text("def f(kem, a, b):\n    ss = kem.decapsulate(a, b)\n"
                   "    raise ValueError(ss)\n")
    assert qrflow_main([str(bad)]) == 1
    capsys.readouterr()
    rc = qrflow_main([str(bad), "--format", "json"])
    assert rc == 1
    payload = json.loads(capsys.readouterr().out)
    assert payload["counts"]["error"] == 1
    (finding,) = payload["findings"]
    assert finding["rule"] == "flow-secret-in-exception"
    assert finding["path"] == str(bad) and finding["line"] == 3
    # selecting an unrelated rule skips the finding; unknown ids error
    assert qrflow_main([str(bad), "--select", "cross-thread-state"]) == 0
    assert qrflow_main([str(bad), "--select", "no-such-rule"]) == 2
    capsys.readouterr()


def test_list_rules(capsys):
    assert qrflow_main(["--list-rules"]) == 0
    out = capsys.readouterr().out
    for rid in ("flow-secret-in-log", "flow-secret-compare",
                "flow-secret-in-trace", "cross-thread-state",
                "asyncio-off-loop", "unjustified-suppression"):
        assert rid in out


# -- fixtures mirroring the PR's live-tree fixes ------------------------------


def test_breaker_race_pattern_fixture():
    """The exact shape fixed in provider/batched.py: a breaker-like object
    quarantined from a warmup thread while loop coroutines record failures —
    unlocked triggers, the shipped lock-guarded twin is clean."""
    src = """
        import threading

        class B:
            def __init__(self):
                self.trips = 0
                {lock_init}

            def record_failure(self):
                {guard}self.trips += 1

            def quarantine(self):
                {guard}self.trips += 1

        class M:
            def __init__(self):
                self.breaker = B()

            def spawn(self):
                threading.Thread(target=self._warm, name="qrp2p-warmup").start()

            def _warm(self):
                self.breaker.quarantine()

            async def dispatch(self):
                self.breaker.record_failure()
        """
    racy = src.format(lock_init="pass", guard="")
    assert "cross-thread-state" in rule_ids(racy)
    fixed = textwrap.dedent(src).format(
        lock_init="self._lock = threading.RLock()",
        guard="with self._lock:\n            ")
    findings, _ = Engine(flow_rules()).lint_source(fixed)
    assert [f.rule for f in findings] == []


def test_rekey_wipe_pattern_fixture():
    """The messaging rekey fix: dropping a session's raw secret without
    wiping leaks its lifetime to the GC — the wipe twin is clean."""
    leak = """
        import logging
        logger = logging.getLogger(__name__)

        def rekey(kem, store, peer, sk, ct):
            old_secret = kem.decapsulate(sk, ct)
            logger.warning("dropping stale secret %s", old_secret)
        """
    assert rule_ids(leak) == ["flow-secret-in-log"]
    clean = """
        import logging
        logger = logging.getLogger(__name__)

        def _wipe(buf):
            pass

        def rekey(kem, store, peer, sk, ct):
            old_secret = kem.decapsulate(sk, ct)
            _wipe(old_secret)
            logger.warning("dropped stale secret (%d bytes)", len(old_secret))
        """
    assert rule_ids(clean) == []


# -- the CI ratchet -----------------------------------------------------------


def test_live_codebase_is_violation_free(capsys):
    """The whole package passes qrflow: every finding is fixed or carries a
    justified inline suppression.  New violations fail here AND in the CI
    qrflow step."""
    rc = qrflow_main([str(PACKAGE)])
    out = capsys.readouterr().out
    assert rc == 0, f"qrflow found new violations:\n{out}"


def test_live_run_is_fast_enough_for_ci():
    """The summary cache keeps the interprocedural fixpoint cheap: the whole
    package must analyze in seconds, not minutes."""
    import time

    contexts = {str(p): FileContext(str(p), p.read_text(encoding="utf-8"))
                for p in sorted(PACKAGE.rglob("*.py"))}
    cg = build_callgraph(Project(contexts))
    t0 = time.perf_counter()
    eng = TaintEngine(cg)
    eng.solve()
    dt = time.perf_counter() - t0
    assert dt < 30.0, f"taint fixpoint took {dt:.1f}s"
    assert eng.cache_hits > 0  # the summary cache is actually being hit
