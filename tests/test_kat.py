"""Known-answer-test runner over tests/vectors/.

Drives every implementation of each algorithm — pure-Python pyref, native
C++ (ctypes), and batched JAX — through the SAME committed vector files, so
a divergence in any one implementation fails loudly.  File provenance is in
each file's "source" field and docs/correctness.md: current vectors are
self-generated (3-way cross-implementation regression anchor); official
NIST/ACVP files use the same runner when dropped in:

  * qrp2p-kat-v1 JSON (this repo's format, large values as sha256 digests)
  * ACVP-style JSON (testGroups/tests with hex fields) via _iter_acvp
  * NIST PQCgenKAT .rsp files (count/seed/... stanzas) via _iter_rsp, with
    utils/ctr_drbg.py reproducing the harness RNG (DRBG verified against the
    canonical published first-seed value in test_ctr_drbg_known_answer)

Reference analog: liboqs KATs are the reference app's correctness anchor
(BASELINE.json "bit-exact vs liboqs KATs"; vendor/oqs.py:310-390).
"""

from __future__ import annotations

import hashlib
import json
from pathlib import Path

import numpy as np
import pytest

from quantum_resistant_p2p_tpu import native
from quantum_resistant_p2p_tpu.pyref import (
    frodo_ref,
    hqc_ref,
    mldsa_ref,
    mlkem_ref,
    slhdsa_ref,
)

VECTOR_DIR = Path(__file__).parent / "vectors"

_HAVE_NATIVE = native.load() is not None


def _load(fname: str) -> dict:
    return json.loads((VECTOR_DIR / fname).read_text())


def _check(rec: dict, key: str, actual: bytes) -> None:
    """Compare against `key` (hex) or `key_sha256` (digest), whichever exists."""
    if key in rec:
        assert actual.hex() == rec[key], f"{key} mismatch"
    elif key + "_sha256" in rec:
        assert hashlib.sha256(actual).hexdigest() == rec[key + "_sha256"], (
            f"{key} digest mismatch"
        )
    else:  # pragma: no cover - malformed vector file
        raise KeyError(f"vector record has neither {key} nor {key}_sha256")


def _b(rec: dict, key: str) -> bytes:
    return bytes.fromhex(rec[key])


# --------------------------------------------------------------------------
# CTR-DRBG: external anchor — this exact value is the first generated seed in
# every published NIST round-3 PQCgenKAT .rsp file (entropy input 00..2F).
# --------------------------------------------------------------------------


def test_ctr_drbg_known_answer():
    pytest.importorskip("cryptography")  # the DRBG is AES-256-CTR
    from quantum_resistant_p2p_tpu.utils.ctr_drbg import CtrDrbg

    drbg = CtrDrbg(bytes(range(48)))
    assert drbg.random_bytes(48).hex().upper() == (
        "061550234D158C5EC95595FE04EF7A25767F2E24CC2BC479D09D86DC9ABCFDE7"
        "056A8C266F9EF97ED08541DBD2E1FFA1"
    )


# --------------------------------------------------------------------------
# ML-KEM
# --------------------------------------------------------------------------

MLKEM_FILES = ["mlkem_512.json", "mlkem_768.json", "mlkem_1024.json"]


@pytest.mark.parametrize("fname", MLKEM_FILES)
def test_mlkem_kat_pyref_and_native(fname):
    data = _load(fname)
    p = mlkem_ref.PARAMS[data["algorithm"]]
    nat = native.NativeMLKEM(data["algorithm"]) if _HAVE_NATIVE else None
    for rec in data["tests"]:
        d, z, m = _b(rec, "d"), _b(rec, "z"), _b(rec, "m")
        ek, dk = mlkem_ref.keygen(p, d, z)
        _check(rec, "ek", ek)
        _check(rec, "dk", dk)
        key, ct = mlkem_ref.encaps(p, ek, m)
        _check(rec, "ct", ct)
        _check(rec, "ss", key)
        assert mlkem_ref.decaps(p, dk, ct) == key
        bad = bytes([ct[0] ^ 1]) + ct[1:]
        _check(rec, "ss_reject", mlkem_ref.decaps(p, dk, bad))
        if nat is not None:
            nek, ndk = nat.keygen(d, z)
            assert (nek, ndk) == (ek, dk)
            nkey, nct = nat.encaps(ek, m)
            assert (nkey, nct) == (key, ct)
            assert nat.decaps(dk, ct) == key
            assert nat.decaps(dk, bad) == mlkem_ref.decaps(p, dk, bad)


@pytest.mark.parametrize(
    "fname",
    ["mlkem_768.json",
     pytest.param("mlkem_512.json", marks=pytest.mark.slow),
     pytest.param("mlkem_1024.json", marks=pytest.mark.slow)],
)
def test_mlkem_kat_jax(fname):
    from quantum_resistant_p2p_tpu.kem import mlkem as jmlkem

    data = _load(fname)
    kg, enc, dec = jmlkem.get(data["algorithm"])
    recs = data["tests"]
    d = np.stack([np.frombuffer(_b(r, "d"), np.uint8) for r in recs])
    z = np.stack([np.frombuffer(_b(r, "z"), np.uint8) for r in recs])
    m = np.stack([np.frombuffer(_b(r, "m"), np.uint8) for r in recs])
    ek, dk = (np.asarray(a) for a in kg(d, z))
    key, ct = enc(ek, m)
    key, ct = np.asarray(key), np.asarray(ct)
    ss2 = np.asarray(dec(dk, ct))
    for i, rec in enumerate(recs):
        _check(rec, "ek", bytes(ek[i]))
        _check(rec, "dk", bytes(dk[i]))
        _check(rec, "ct", bytes(ct[i]))
        _check(rec, "ss", bytes(key[i]))
        assert bytes(ss2[i]) == bytes(key[i])


# --------------------------------------------------------------------------
# ML-DSA
# --------------------------------------------------------------------------

MLDSA_FILES = ["mldsa_44.json", "mldsa_65.json", "mldsa_87.json"]


@pytest.mark.parametrize("fname", MLDSA_FILES)
def test_mldsa_kat_pyref_and_native(fname):
    data = _load(fname)
    p = mldsa_ref.PARAMS[data["algorithm"]]
    nat = native.NativeMLDSA(data["algorithm"]) if _HAVE_NATIVE else None
    for rec in data["tests"]:
        xi, rnd, msg = _b(rec, "xi"), _b(rec, "rnd"), _b(rec, "msg")
        m_prime = bytes([0, 0]) + msg
        pk, sk = mldsa_ref.keygen(p, xi)
        _check(rec, "pk", pk)
        _check(rec, "sk", sk)
        sig = mldsa_ref.sign_internal(p, sk, m_prime, rnd)
        _check(rec, "sig", sig)
        assert mldsa_ref.verify_internal(p, pk, m_prime, sig)
        if nat is not None:
            assert nat.keygen(xi) == (pk, sk)
            assert nat.sign_internal(sk, m_prime, rnd) == sig
            assert nat.verify_internal(pk, m_prime, sig)


@pytest.mark.parametrize(
    "fname",
    # 44 runs in the fast tier as the JAX coverage for that parameter set
    # (its oracle sign test is slow-tier; see tests/test_mldsa.py).
    ["mldsa_65.json", "mldsa_44.json",
     pytest.param("mldsa_87.json", marks=pytest.mark.slow)],
)
def test_mldsa_kat_jax(fname):
    import hashlib as _hl

    from quantum_resistant_p2p_tpu.sig import mldsa as jmldsa

    data = _load(fname)
    p = mldsa_ref.PARAMS[data["algorithm"]]
    kg, sign_mu, verify_mu = jmldsa.get(data["algorithm"])
    recs = data["tests"]
    xi = np.stack([np.frombuffer(_b(r, "xi"), np.uint8) for r in recs])
    pk, sk = (np.asarray(a) for a in kg(xi))
    mus, rnds = [], []
    for i, rec in enumerate(recs):
        _check(rec, "pk", bytes(pk[i]))
        _check(rec, "sk", bytes(sk[i]))
        tr = bytes(sk[i][64:128])
        m_prime = bytes([0, 0]) + _b(rec, "msg")
        mus.append(np.frombuffer(_hl.shake_256(tr + m_prime).digest(64), np.uint8))
        rnds.append(np.frombuffer(_b(rec, "rnd"), np.uint8))
    sigs, done = sign_mu(sk, np.stack(mus), np.stack(rnds))
    sigs = np.asarray(sigs)
    assert bool(np.asarray(done).all())
    for i, rec in enumerate(recs):
        _check(rec, "sig", bytes(sigs[i]))
    ok = np.asarray(verify_mu(pk, np.stack(mus), sigs))
    assert ok.all()


# --------------------------------------------------------------------------
# SLH-DSA
# --------------------------------------------------------------------------

SLHDSA_FILES = [
    "slhdsa_128s.json", "slhdsa_128f.json",
    pytest.param("slhdsa_192s.json", marks=pytest.mark.slow),
    pytest.param("slhdsa_192f.json", marks=pytest.mark.slow),
    pytest.param("slhdsa_256s.json", marks=pytest.mark.slow),
    pytest.param("slhdsa_256f.json", marks=pytest.mark.slow),
]


@pytest.mark.parametrize("fname", SLHDSA_FILES)
def test_slhdsa_kat_native(fname):
    if not _HAVE_NATIVE:
        pytest.skip("no C++ toolchain")
    data = _load(fname)
    nat = native.NativeSLHDSA(data["algorithm"])
    for rec in data["tests"]:
        ss, sp, ps = _b(rec, "sk_seed"), _b(rec, "sk_prf"), _b(rec, "pk_seed")
        msg = _b(rec, "msg")
        pk, sk = nat.keygen(ss, sp, ps)
        _check(rec, "pk", pk)
        sig = nat.sign_internal(msg, sk)
        _check(rec, "sig", sig)
        assert nat.verify_internal(msg, sig, pk)


@pytest.mark.parametrize("fname", ["slhdsa_128f.json"])
def test_slhdsa_kat_pyref(fname):
    """Fast tier on purpose: the only toolchain-independent SPHINCS+ vector
    check (native tests skip without g++, the JAX module is slow-tier)."""
    data = _load(fname)
    p = slhdsa_ref.PARAMS[data["algorithm"]]
    rec = data["tests"][0]
    pk, sk = slhdsa_ref.keygen(p, _b(rec, "sk_seed"), _b(rec, "sk_prf"), _b(rec, "pk_seed"))
    _check(rec, "pk", pk)
    sig = slhdsa_ref.sign_internal(p, _b(rec, "msg"), sk, None)
    _check(rec, "sig", sig)


@pytest.mark.slow
@pytest.mark.parametrize("fname", ["slhdsa_128f.json"])
def test_slhdsa_kat_jax(fname):
    from quantum_resistant_p2p_tpu.sig import sphincs as jslh

    data = _load(fname)
    p = slhdsa_ref.PARAMS[data["algorithm"]]
    kg, sign_digest, verify_digest = jslh.get(data["algorithm"])
    recs = data["tests"]
    ss = np.stack([np.frombuffer(_b(r, "sk_seed"), np.uint8) for r in recs])
    sp = np.stack([np.frombuffer(_b(r, "sk_prf"), np.uint8) for r in recs])
    ps = np.stack([np.frombuffer(_b(r, "pk_seed"), np.uint8) for r in recs])
    pk, sk = (np.asarray(a) for a in kg(ss, sp, ps))
    rs, digests = [], []
    for i, rec in enumerate(recs):
        _check(rec, "pk", bytes(pk[i]))
        msg = _b(rec, "msg")
        skb = bytes(sk[i])
        r = slhdsa_ref.prf_msg(p, skb[p.n:2 * p.n], skb[2 * p.n:3 * p.n], msg)
        rs.append(np.frombuffer(r, np.uint8))
        digests.append(np.frombuffer(
            slhdsa_ref.h_msg(p, r, skb[2 * p.n:3 * p.n], skb[3 * p.n:], msg), np.uint8))
    sigs = np.asarray(sign_digest(sk, np.stack(rs), np.stack(digests)))
    for i, rec in enumerate(recs):
        _check(rec, "sig", bytes(sigs[i]))
    assert np.asarray(verify_digest(pk, np.stack(digests), sigs)).all()


# --------------------------------------------------------------------------
# FrodoKEM / HQC
# --------------------------------------------------------------------------

FRODO_FILES = [
    "frodo_640_aes.json",
    pytest.param("frodo_640_shake.json", marks=pytest.mark.slow),
    pytest.param("frodo_976_aes.json", marks=pytest.mark.slow),
    pytest.param("frodo_976_shake.json", marks=pytest.mark.slow),
    pytest.param("frodo_1344_aes.json", marks=pytest.mark.slow),
    pytest.param("frodo_1344_shake.json", marks=pytest.mark.slow),
]


@pytest.mark.parametrize("fname", FRODO_FILES)
def test_frodo_kat_pyref(fname):
    if "aes" in fname:
        pytest.importorskip("cryptography")  # AES matrix expansion
    data = _load(fname)
    p = frodo_ref.PARAMS[data["algorithm"]]
    for rec in data["tests"][:1]:
        pk, sk = frodo_ref.keygen(p, _b(rec, "s"), _b(rec, "seed_se"), _b(rec, "z"))
        _check(rec, "pk", pk)
        _check(rec, "sk", sk)
        ct, ss = frodo_ref.encaps(p, pk, _b(rec, "mu"))
        _check(rec, "ct", ct)
        _check(rec, "ss", ss)
        assert frodo_ref.decaps(p, sk, ct) == ss


HQC_FILES = [
    "hqc_128.json",
    pytest.param("hqc_192.json", marks=pytest.mark.slow),
    pytest.param("hqc_256.json", marks=pytest.mark.slow),
]


@pytest.mark.parametrize("fname", HQC_FILES)
def test_hqc_kat_pyref(fname):
    data = _load(fname)
    p = hqc_ref.PARAMS[data["algorithm"]]
    for rec in data["tests"][:1]:
        pk, sk = hqc_ref.keygen(p, _b(rec, "sk_seed"), _b(rec, "sigma"), _b(rec, "pk_seed"))
        _check(rec, "pk", pk)
        _check(rec, "sk", sk)
        ct, ss = hqc_ref.encaps(p, pk, _b(rec, "m"), _b(rec, "salt"))
        _check(rec, "ct", ct)
        _check(rec, "ss", ss)
        assert hqc_ref.decaps(p, sk, ct) == ss


# --------------------------------------------------------------------------
# Official-format drop-in support: ACVP JSON and NIST .rsp
# --------------------------------------------------------------------------


def _iter_acvp(data: dict):
    """Yield flat test dicts from an ACVP-style {testGroups: [{tests: []}]}."""
    for group in data.get("testGroups", []):
        meta = {k: v for k, v in group.items() if k != "tests"}
        for t in group.get("tests", []):
            yield {**meta, **t}


def _iter_rsp(text: str):
    """Yield stanza dicts from a NIST PQCgenKAT .rsp file."""
    rec: dict = {}
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            if rec:
                yield rec
                rec = {}
            continue
        if "=" in line:
            k, _, v = line.partition("=")
            rec[k.strip()] = v.strip()
    if rec:
        yield rec


def test_acvp_dropin_mlkem():
    """Official ACVP ML-KEM files run through this path; validated here with a
    generated fixture in the same shape (d/z/ek/dk, ek/m/c/k hex fields)."""
    files = sorted(VECTOR_DIR.glob("acvp_mlkem*.json"))
    if not files:
        pytest.skip("no ACVP ML-KEM files present")
    for f in files:
        data = json.loads(f.read_text())
        algo = data.get("algorithm", "ML-KEM-768")
        name = algo if algo.startswith("ML-KEM") else "ML-KEM-768"
        p = mlkem_ref.PARAMS[name]
        for t in _iter_acvp(data):
            if "d" in t and "z" in t:  # keygen case
                ek, dk = mlkem_ref.keygen(p, bytes.fromhex(t["d"]), bytes.fromhex(t["z"]))
                assert ek.hex() == t["ek"].lower() and dk.hex() == t["dk"].lower()
            if "m" in t and "ek" in t:  # encap case
                k, c = mlkem_ref.encaps(p, bytes.fromhex(t["ek"]), bytes.fromhex(t["m"]))
                assert c.hex() == t["c"].lower() and k.hex() == t["k"].lower()
            if "dk" in t and "c" in t:  # decap case
                k = mlkem_ref.decaps(p, bytes.fromhex(t["dk"]), bytes.fromhex(t["c"]))
                assert k.hex() == t["k"].lower()


def test_rsp_parser_roundtrip(tmp_path):
    """The .rsp stanza parser + DRBG path official FrodoKEM/Kyber KAT files
    use; proven on a generated stanza file."""
    pytest.importorskip("cryptography")  # the DRBG is AES-256-CTR
    from quantum_resistant_p2p_tpu.utils.ctr_drbg import CtrDrbg

    master = CtrDrbg(bytes(range(48)))
    seeds = [master.random_bytes(48) for _ in range(3)]
    lines = ["# generated fixture", ""]
    for i, seed in enumerate(seeds):
        lines += [f"count = {i}", f"seed = {seed.hex().upper()}", ""]
    f = tmp_path / "fixture.rsp"
    f.write_text("\n".join(lines))
    recs = list(_iter_rsp(f.read_text()))
    assert [int(r["count"]) for r in recs] == [0, 1, 2]
    assert [r["seed"].lower() for r in recs] == [s.hex() for s in seeds]
    # per-count DRBG reseed, as PQCgenKAT does before each keypair call
    sub = CtrDrbg(seeds[0])
    assert len(sub.random_bytes(64)) == 64


def test_hqc_official_mismatch_diagnosis():
    """The HQC divergence-diagnosis decision tree pinpoints which seam
    assumption a failing official .rsp refutes: synthesize stanzas with
    each enumerable variant seam and assert the diagnosis names it
    (docs/correctness.md §HQC seam)."""
    pytest.importorskip("cryptography")  # the DRBG is AES-256-CTR
    from quantum_resistant_p2p_tpu.pyref import hqc_ref
    from quantum_resistant_p2p_tpu.utils.ctr_drbg import CtrDrbg
    from tools.verify_vectors import (
        _hqc_encrypt_order,
        _hqc_keygen_order,
        check_rsp_hqc,
    )

    p = hqc_ref.PARAMS["HQC-128"]
    seed = bytes(range(48))
    # Per-call DRBG semantics (each randombytes call pads to the AES block
    # and rekeys) — the draws must be made exactly like the checker's.
    drbg = CtrDrbg(seed)
    sk_seed, sigma, pk_seed = (
        drbg.random_bytes(40), drbg.random_bytes(p.k), drbg.random_bytes(40)
    )
    m, salt = drbg.random_bytes(p.k), drbg.random_bytes(16)

    def stanza(pk, sk, ct, ss):
        return "\n".join(
            ["count = 0", f"seed = {seed.hex().upper()}",
             f"pk = {pk.hex().upper()}", f"sk = {sk.hex().upper()}",
             f"ct = {ct.hex().upper()}", f"ss = {ss.hex().upper()}", ""]
        )

    # implemented seam reproduces its own stanza (sanity)
    pk, sk = hqc_ref.keygen(p, sk_seed, sigma, pk_seed)
    ct, ss = hqc_ref.encaps(p, pk, m, salt)
    n, ok, errors = check_rsp_hqc(stanza(pk, sk, ct, ss), "PQCgenKAT_hqc128.rsp")
    assert (n, ok) == (1, 1), errors

    # variant: round-3 x-before-y sk draw order
    pk_v = _hqc_keygen_order(p, sk_seed, sigma, pk_seed, x_first=True)
    ct_v, ss_v = hqc_ref.encaps(p, pk_v, m, salt)
    _, ok, errors = check_rsp_hqc(
        stanza(pk_v, sk_seed + sigma + pk_v, ct_v, ss_v), "PQCgenKAT_hqc128.rsp"
    )
    assert ok == 0 and any("ROUND-3 sk-draw order" in e for e in errors), errors

    # variant: pk_seed drawn before sk_seed
    d2 = CtrDrbg(seed)
    pk_seed_b, sk_seed_b, sigma_b = (
        d2.random_bytes(40), d2.random_bytes(40), d2.random_bytes(p.k)
    )
    _, ok, errors = check_rsp_hqc(
        stanza(*hqc_ref.keygen(p, sk_seed_b, sigma_b, pk_seed_b), ct, ss),
        "PQCgenKAT_hqc128.rsp",
    )
    assert ok == 0 and any("drawn FIRST" in e for e in errors), errors

    # variant: theta-expander draw order r1,r2,e instead of r2,e,r1
    theta = hqc_ref._hash_g(m + pk[:32] + salt)
    u, v = _hqc_encrypt_order(p, pk, m, theta, ("r1", "r2", "e"))
    ct_o = (u.to_bytes(p.n_bytes, "little")
            + v.to_bytes(p.n1n2_bytes, "little") + salt)
    ss_o = hqc_ref._hash_k(m + ct_o[:-16])
    _, ok, errors = check_rsp_hqc(stanza(pk, sk, ct_o, ss_o), "PQCgenKAT_hqc128.rsp")
    assert ok == 0 and any(
        "VARIANT" in e and "r1>r2>e" in e for e in errors
    ), errors


def test_verify_vectors_all_families():
    """tools/verify_vectors.py over the committed vector dir: every family
    has at least a fixture exercising its official-format parser + DRBG
    seam, and everything present passes."""
    pytest.importorskip("cryptography")  # .rsp verification drives the DRBG
    from tools.verify_vectors import verify_directory

    report = verify_directory(VECTOR_DIR)
    for family, fam in report.items():
        assert fam["files"], f"{family}: no official-format fixture committed"
        assert fam["status"] != "FAIL", (family, fam["errors"])
        assert fam["vectors"] == fam["passed"] > 0
