"""Algorithm-combination compatibility harness.

Port of the reference's tests/crypto_algorithms_tester.py (1169 LoC): two full
in-process node stacks on localhost TCP, every KEM x AEAD x SIG combination
exercised end-to-end (key exchange, bidirectional messaging, file transfers at
three sizes), results collected into a PASS/FAIL report with throughput
rankings (reference: :452-544 run loop, :893-1094 report).

The reference matrix is 9 KEMs x 2 AEADs x 6 SIGs = 108; this framework's
registry also splits FrodoKEM into AES/SHAKE variants (12 KEMs -> 144 combos).

Usage:
  python -m tools.compat_matrix --quick              # ML-KEM x everything
  python -m tools.compat_matrix --backend tpu        # full matrix on TPU
  python -m tools.compat_matrix --kems ML-KEM-768 --sigs ML-DSA-65
"""

from __future__ import annotations

import argparse
import asyncio
import json
import os
import sys
import time
from dataclasses import dataclass, field
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

from quantum_resistant_p2p_tpu.app.message_store import Message  # noqa: E402
from quantum_resistant_p2p_tpu.app.messaging import SecureMessaging  # noqa: E402
from quantum_resistant_p2p_tpu.net.p2p_node import P2PNode  # noqa: E402
from quantum_resistant_p2p_tpu.provider import (  # noqa: E402
    get_kem,
    get_signature,
    get_symmetric,
    list_kems,
    list_signatures,
    list_symmetrics,
)
from quantum_resistant_p2p_tpu.storage.key_storage import KeyStorage  # noqa: E402

FILE_SIZES = {"10KB": 10 * 1024, "100KB": 100 * 1024, "1MB": 1024 * 1024}


@dataclass
class ComboResult:
    kem: str
    aead: str
    sig: str
    connected: bool = False
    key_exchange_ok: bool = False
    key_exchange_time: float = 0.0
    messaging_ok: bool = False
    file_results: dict = field(default_factory=dict)  # label -> KB/s or None
    error: str | None = None

    @property
    def passed(self) -> bool:
        return (
            self.connected
            and self.key_exchange_ok
            and self.messaging_ok
            and all(v is not None for v in self.file_results.values())
        )


class TestNode:
    """Full stack minus UI (reference TestNode, crypto_algorithms_tester.py:49)."""

    def __init__(self, name: str, workdir: Path, backend: str):
        self.name = name
        self.backend = backend
        self.storage = KeyStorage(workdir / f"{name}.vault.json")
        assert self.storage.unlock("test_password")
        self.node = P2PNode(node_id=name, host="127.0.0.1", port=0)
        self.messaging: SecureMessaging | None = None
        self.inbox: list[Message] = []
        self.got = asyncio.Event()

    async def start(self):
        await self.node.start()
        self.messaging = SecureMessaging(
            self.node, key_storage=self.storage, backend=self.backend
        )
        self.messaging.register_message_listener(self._on_msg)

    def _on_msg(self, peer_id: str, message: Message):
        if not message.is_system:
            self.inbox.append(message)
            self.got.set()

    def configure(self, kem: str, aead: str, sig: str):
        m = self.messaging
        m.kem = get_kem(kem, self.backend)
        m.symmetric = get_symmetric(aead)
        m.signature = get_signature(sig, self.backend)
        m._sig_keypair = m._load_or_generate_sig_keypair()
        if m.use_batching:
            from quantum_resistant_p2p_tpu.provider.batched import (
                BatchedKEM,
                BatchedSignature,
            )

            m._bkem = BatchedKEM(m.kem, *m._batch_cfg)
            m._bsig = BatchedSignature(m.signature, *m._batch_cfg)

    def reset_keys(self):
        m = self.messaging
        m.shared_keys.clear()
        m.raw_secrets.clear()
        m.ke_state.clear()

    async def wait_message(self, pred, timeout=30.0) -> Message | None:
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            for msg in self.inbox:
                if pred(msg):
                    return msg
            self.got.clear()
            try:
                await asyncio.wait_for(self.got.wait(), 0.25)
            except asyncio.TimeoutError:
                pass
        return None

    async def stop(self):
        await self.node.stop()


async def run_combo(a: TestNode, b: TestNode, kem: str, aead: str, sig: str,
                    payloads: dict[str, bytes]) -> ComboResult:
    r = ComboResult(kem, aead, sig)
    a.configure(kem, aead, sig)
    b.configure(kem, aead, sig)
    a.reset_keys()
    b.reset_keys()
    a.inbox.clear()
    b.inbox.clear()
    r.connected = a.node.is_connected(b.name)
    if not r.connected:
        r.error = "not connected"
        return r
    # Re-gossip the new settings and wait for both sides to see them
    # (reference: settings-sync retry loop, crypto_algorithms_tester.py:617-643).
    await a.messaging.notify_peers_of_settings_change()
    await b.messaging.notify_peers_of_settings_change()
    for _ in range(200):
        if (a.messaging.settings_match(b.name) is True
                and b.messaging.settings_match(a.name) is True):
            break
        await asyncio.sleep(0.01)
    else:
        r.error = "settings gossip did not converge"
        return r
    t0 = time.perf_counter()
    try:
        ok = await a.messaging.initiate_key_exchange(b.name)
    except Exception as e:
        r.error = f"key exchange raised: {e}"
        return r
    r.key_exchange_time = time.perf_counter() - t0
    # both sides must hold the key (reference: :665-672)
    for _ in range(200):
        if b.name in a.messaging.shared_keys and a.name in b.messaging.shared_keys:
            break
        await asyncio.sleep(0.01)
    r.key_exchange_ok = bool(ok) and a.messaging.shared_keys.get(
        b.name
    ) == b.messaging.shared_keys.get(a.name)
    if not r.key_exchange_ok:
        r.error = "key exchange failed"
        return r
    # bidirectional messaging
    ping = f"ping {kem}/{aead}/{sig}".encode()
    await a.messaging.send_message(b.name, ping)
    got = await b.wait_message(lambda m: m.content == ping)
    pong = b"pong " + ping
    await b.messaging.send_message(a.name, pong)
    got2 = await a.wait_message(lambda m: m.content == pong)
    r.messaging_ok = got is not None and got2 is not None
    if not r.messaging_ok:
        r.error = "messaging failed"
        return r
    # file transfers with throughput (reference: :754-849)
    for label, payload in payloads.items():
        t0 = time.perf_counter()
        sent = await a.messaging.send_message(b.name, payload, is_file=True,
                                              filename=f"{label}.bin")
        got = await b.wait_message(
            lambda m: m.is_file and m.filename == f"{label}.bin"
        )
        dt = time.perf_counter() - t0
        if sent is None or got is None or got.content != payload:
            r.file_results[label] = None
            r.error = f"file {label} failed"
        else:
            r.file_results[label] = round(len(payload) / 1024 / dt, 2)
    return r


def make_report(results: list[ComboResult], out_dir: Path, backend: str) -> dict:
    out_dir.mkdir(parents=True, exist_ok=True)
    passed = [r for r in results if r.passed]
    by_throughput = sorted(
        (r for r in passed if r.file_results),
        key=lambda r: -(sum(v for v in r.file_results.values() if v) / max(len(r.file_results), 1)),
    )
    report = {
        "backend": backend,
        "total": len(results),
        "passed": len(passed),
        "failed": len(results) - len(passed),
        "results": [r.__dict__ for r in results],
        "fastest_key_exchange": sorted(
            ({"combo": f"{r.kem}+{r.sig}", "seconds": round(r.key_exchange_time, 4)}
             for r in passed),
            key=lambda d: d["seconds"],
        )[:10],
        "best_throughput": [
            {
                "combo": f"{r.aead}+{r.sig}",
                "avg_kb_s": round(
                    sum(v for v in r.file_results.values() if v) / max(len(r.file_results), 1), 1
                ),
            }
            for r in by_throughput[:10]
        ],
    }
    stamp = time.strftime("%Y%m%d_%H%M%S")
    (out_dir / f"compat_report_{stamp}.json").write_text(json.dumps(report, indent=2))
    lines = [f"Compatibility report — backend={backend}",
             f"{len(passed)}/{len(results)} combinations passed", ""]
    for r in results:
        mark = "PASS" if r.passed else f"FAIL ({r.error})"
        lines.append(
            f"  {r.kem:24s} {r.aead:20s} {r.sig:30s} {mark}"
            f"  ke={r.key_exchange_time:.3f}s files={r.file_results}"
        )
    (out_dir / f"compat_report_{stamp}.txt").write_text("\n".join(lines))
    return report


async def run_matrix(kems, aeads, sigs, backend: str, out_dir: Path,
                     file_sizes=FILE_SIZES) -> dict:
    import tempfile

    workdir = Path(tempfile.mkdtemp(prefix="qrp2p_tpu_compat_"))
    payloads = {label: os.urandom(size) for label, size in file_sizes.items()}
    a = TestNode("server", workdir, backend)
    b = TestNode("client", workdir, backend)
    await a.start()
    await b.start()
    assert await b.node.connect_to_peer("127.0.0.1", a.node.port)
    for _ in range(200):
        if a.node.is_connected("client"):
            break
        await asyncio.sleep(0.01)

    results = []
    for kem in kems:
        for aead in aeads:
            for sig in sigs:
                print(f"[{len(results) + 1}] {kem} + {aead} + {sig} ...",
                      flush=True)
                r = await run_combo(b, a, kem, aead, sig, payloads)
                print(f"    -> {'PASS' if r.passed else 'FAIL: ' + str(r.error)}"
                      f"  ke={r.key_exchange_time:.3f}s", flush=True)
                results.append(r)
    await a.stop()
    await b.stop()
    return make_report(results, out_dir, backend)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--backend", default="cpu", choices=("cpu", "tpu", "auto"))
    ap.add_argument("--kems", nargs="*", default=None)
    ap.add_argument("--aeads", nargs="*", default=None)
    ap.add_argument("--sigs", nargs="*", default=None)
    ap.add_argument("--quick", action="store_true",
                    help="ML-KEM-only KEMs, small files")
    ap.add_argument("--output-dir", default="bench_results")
    args = ap.parse_args(argv)

    kems = args.kems or ([k for k in list_kems() if k.startswith("ML-KEM")]
                         if args.quick else list_kems())
    aeads = args.aeads or list_symmetrics()
    sigs = args.sigs or ([s for s in list_signatures() if s.startswith("ML-DSA")]
                         if args.quick else list_signatures())
    sizes = {"10KB": 10240, "100KB": 102400} if args.quick else FILE_SIZES

    report = asyncio.run(
        run_matrix(kems, aeads, sigs, args.backend, Path(args.output_dir), sizes)
    )
    print(json.dumps({k: report[k] for k in ("backend", "total", "passed", "failed")}))
    return 0 if report["failed"] == 0 else 1


if __name__ == "__main__":
    sys.exit(main())
