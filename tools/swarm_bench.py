"""Simulated peer-swarm benchmark (BASELINE.json config 5).

N client stacks connect to one hub node over real localhost TCP and run the
full authenticated 5-message handshake concurrently, with the hub's (and
clients') KEM/signature ops coalescing in the TPU batch queue; then every
client sends one AEAD message.  Reports handshakes/sec, p50/p99 handshake
latency, and end-to-end msgs/sec as ONE JSON line.

Reference analog: tests/crypto_algorithms_tester.py runs exactly two nodes
(reference :455-464); the swarm scales that shape to 1000 peers, which is the
point of the batching refactor (SURVEY.md §2.3 "data parallelism").

Usage: python -m tools.swarm_bench --peers 1000 --backend tpu --batch
"""

from __future__ import annotations

import argparse
import asyncio
import json
import statistics
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

from quantum_resistant_p2p_tpu.app.messaging import SecureMessaging  # noqa: E402
from quantum_resistant_p2p_tpu.fleet.stormlib import (  # noqa: E402
    StormAEAD as _StormAEAD, prewarm_facades as _prewarm_facades,
    register_storm_providers as _register_storm_providers,
    storm_env as _storm_env)
from quantum_resistant_p2p_tpu.net.p2p_node import P2PNode  # noqa: E402


async def run_swarm(n_peers: int, backend: str, use_batching: bool,
                    max_batch: int, max_wait_ms: float, concurrency: int,
                    warmup: int = 0, ke_timeout: float = 180.0,
                    batch_floor: int = 1, prewarm: bool = False,
                    slo: bool = False, shard_devices: int = 0) -> dict:
    """``slo=True`` turns the swarm into the single-handshake SLO probe:
    handshakes only (no AEAD message rides in the measured window, so the
    breaker-delta trip accounting below is handshake-pure) and per-handshake
    dispatch-trip stats in the output.  Meaningful at concurrency 1 —
    overlapping handshakes share the breaker counters."""
    # Cold-compile of each batch-size bucket can take tens of seconds on a
    # fresh machine; a generous protocol timeout plus an untimed warmup round
    # keeps compiles out of the measured numbers.
    from quantum_resistant_p2p_tpu.app import messaging as _messaging

    if backend != "cpu":
        from quantum_resistant_p2p_tpu.utils.benchmarking import enable_compile_cache

        enable_compile_cache()
    # host AEAD: AES-256-GCM when the OpenSSL wheel is present (the
    # historical r4/r5 configuration); on wheel-less images the bench-only
    # stdlib AEAD keeps the PQ pipeline measurable — the swap touches only
    # the ke_test probe + message AEAD, never the KEM/signature device
    # path, and the emitted JSON says which one ran (the "aead" field)
    import importlib.util

    aead_kw = {}
    if importlib.util.find_spec("cryptography") is None:
        aead_kw = {"symmetric": _StormAEAD()}
    _messaging.KEY_EXCHANGE_TIMEOUT = ke_timeout
    hub_node = P2PNode(node_id="hub", host="127.0.0.1", port=0)
    await hub_node.start()
    hub = SecureMessaging(
        hub_node, backend=backend, use_batching=use_batching,
        max_batch=max_batch, max_wait_ms=max_wait_ms, batch_floor=batch_floor,
        shard_devices=shard_devices, **aead_kw,
    )
    received = 0
    got_all = asyncio.Event()

    def on_msg(peer_id, message):
        nonlocal received
        if not message.is_system:
            received += 1
            if received >= n_peers:
                got_all.set()

    hub.register_message_listener(on_msg)

    # Shared algorithm objects across clients: one jitted program, one queue.
    proto = SecureMessaging(
        P2PNode(node_id="proto", host="127.0.0.1", port=0),
        backend=backend, use_batching=use_batching,
        max_batch=max_batch, max_wait_ms=max_wait_ms, batch_floor=batch_floor,
        shard_devices=shard_devices, **aead_kw,
    )

    # size-1 buckets precompile in the background at construction; wait so
    # warmup clients start against a warm provider
    await hub.wait_ready()
    await proto.wait_ready()

    prewarm_s = 0.0
    if prewarm and use_batching and hub._bkem is not None:
        # The round-3 lesson (VERDICT weak #1): without this, every pow2
        # flush bucket between the floor and the concurrency level starts
        # cold, the degrade path serves ~all live ops from the cpu, and the
        # "tpu" swarm never demonstrates the north-star pipeline.  Warm
        # EVERY bucket a live flush can land in, on BOTH facades (the hub's
        # queues are separate objects from the shared client queues; same
        # jitted programs, so the second facade's warmup is a cache hit).
        # every pow2 bucket from the facade's (rounded) floor up to the
        # concurrency level — at least the floor bucket itself, which is
        # what all flushes use when the floor exceeds concurrency
        b = hub._bkem.bucket_floor
        t0 = time.perf_counter()
        sizes = await _prewarm_facades(
            (proto._bkem, proto._bsig, hub._bkem, hub._bsig,
             proto._bfused, hub._bfused),
            min(max_batch, max(b, concurrency, 1)), floor=b)
        prewarm_s = time.perf_counter() - t0
        print(f"prewarm: buckets {sizes} on 4 facades in {prewarm_s:.1f}s",
              file=sys.stderr)

    clients: list[SecureMessaging] = []
    latencies: list[float] = []
    sem = asyncio.Semaphore(concurrency)

    # Pre-generate every client's long-lived sig keypair in ONE device
    # batch: 1000 serial scalar keygens at construction measured ~0.2s each
    # and dominated wall time (a real peer boots once; the benchmark
    # measures the handshake pipeline).
    n_keys = n_peers + warmup
    # pow2 pad only where it buys a single compiled shape (the jitted tpu
    # path); the cpu path loops scalar keygens and padding is pure waste
    n_alloc = (1 << max(0, n_keys - 1).bit_length()) if backend == "tpu" else n_keys
    kp_pks, kp_sks = proto.signature.generate_keypair_batch(n_alloc)
    kp_next = iter(range(n_keys))

    def make_client(i: int) -> SecureMessaging:
        j = next(kp_next)
        node = P2PNode(node_id=f"peer{i:04d}", host="127.0.0.1", port=0)
        sm = SecureMessaging(node, backend=backend, kem=proto.kem,
                             symmetric=proto.symmetric, signature=proto.signature,
                             sig_keypair=(bytes(kp_pks[j]), bytes(kp_sks[j])))
        # share the batch queues so all clients coalesce into the same batches
        sm._bkem, sm._bsig = proto._bkem, proto._bsig
        sm._bfused = proto._bfused
        sm.use_batching = use_batching
        clients.append(sm)
        return sm

    async def drive_client(i: int, sm: SecureMessaging) -> None:
        async with sem:
            assert await sm.node.connect_to_peer("127.0.0.1", hub_node.port) == "hub"
            t0 = time.perf_counter()
            ok = await sm.initiate_key_exchange("hub")
            latencies.append(time.perf_counter() - t0)
            if not ok:
                raise RuntimeError(f"handshake {i} failed")
            if not slo:
                await sm.send_message("hub", b"hello from peer %d" % i)

    async def one_client(i: int) -> None:
        await drive_client(i, make_client(i))

    if warmup:
        warm = await asyncio.gather(*(one_client(-i - 1) for i in range(warmup)),
                                    return_exceptions=True)
        warm_fail = sum(1 for r in warm if isinstance(r, Exception))
        if warm_fail:
            print(f"warmup: {warm_fail}/{warmup} failed", file=sys.stderr)
        latencies.clear()
        received = 0
        got_all.clear()
        # the warmup clients stay in `clients`; drop their trip samples so
        # initiator_trips_* describes only the measured (warm) window (the
        # histogram is an obs-registry instrument now — reset in place so
        # the registry keeps pointing at the live object)
        for sm in clients:
            sm._handshake_trips.reset()
        # QueueStats are cumulative; reset so device_served_pct and the
        # dispatch histograms describe ONLY the measured window (warmup
        # ops land on cold buckets / the fallback by design)
        if use_batching and hub._bkem is not None:
            from quantum_resistant_p2p_tpu.provider.batched import QueueStats

            facades = [hub._bkem, hub._bsig, proto._bkem, proto._bsig]
            facades += [f for f in (hub._bfused, proto._bfused) if f is not None]
            for facade in facades:
                for q in (facade.__dict__.get("_kg"), facade.__dict__.get("_enc"),
                          facade.__dict__.get("_dec"), facade.__dict__.get("_sign"),
                          facade.__dict__.get("_verify")):
                    if q is not None:
                        q.stats = QueueStats()

    # pre-build every client stack, then start the measured window
    pre = [make_client(i) for i in range(n_peers)]

    def _breaker_trips() -> int:
        # serial dispatch steps (device + cpu fallback) across BOTH sides'
        # breakers — the per-handshake SLO currency (docs/dispatch_budget.md),
        # through the one definition SecureMessaging uses
        return proto._trips_now() + hub._trips_now()

    trips0 = _breaker_trips()
    t_start = time.perf_counter()
    results = await asyncio.gather(*(drive_client(i, sm)
                                     for i, sm in enumerate(pre)),
                                   return_exceptions=True)
    failures = [r for r in results if isinstance(r, Exception)]
    if not slo:
        try:
            await asyncio.wait_for(got_all.wait(), 60)
        except asyncio.TimeoutError:
            pass
    elapsed = time.perf_counter() - t_start
    trips_delta = _breaker_trips() - trips0

    slo_report = None
    if slo:
        # SLO engine evaluation while the plane is still alive (obs/slo.py):
        # the hub is the responder/gateway side; the initiator-side latency
        # split aggregates every client stack's histogram against the same
        # threshold the engines alert on
        from quantum_resistant_p2p_tpu.app.messaging import (
            HANDSHAKE_SLO_THRESHOLD_S)
        from quantum_resistant_p2p_tpu.obs import slo as obs_slo

        good = bad = 0.0
        for sm in clients:
            g, b = obs_slo.latency_probe(sm._handshake_latency,
                                         HANDSHAKE_SLO_THRESHOLD_S)()
            good += g
            bad += b
        slo_report = {
            "hub": hub.slo_status(),
            "client_plane": proto.slo_status(),
            "initiator_handshake": {
                "threshold_s": HANDSHAKE_SLO_THRESHOLD_S,
                "good": good,
                "bad": bad,
            },
        }

    for sm in clients:
        await sm.node.stop()
    await hub_node.stop()

    lat_sorted = sorted(latencies)
    stats = {
        "peers": n_peers,
        "backend": backend,
        "aead": hub.symmetric.display_name,
        "batching": use_batching,
        "failures": len(failures),
        "elapsed_s": round(elapsed, 3),
        "handshakes_per_s": round(len(latencies) / elapsed, 2),
        "e2e_msgs_per_s": round(received / elapsed, 2),
        "p50_handshake_s": round(statistics.median(lat_sorted), 4) if lat_sorted else None,
        "p99_handshake_s": round(
            lat_sorted[max(0, int(len(lat_sorted) * 0.99) - 1)], 4
        ) if lat_sorted else None,
        "messages_received": received,
    }
    if use_batching and hub._bkem is not None:
        stats["prewarm_s"] = round(prewarm_s, 1)
        stats["batch_floor"] = batch_floor
        stats["shard_devices"] = shard_devices
        if hub._scheduler is not None and hub._scheduler.n_shards > 1:
            stats["shards"] = {
                "hub": hub._scheduler.stats(),
                "client": proto._scheduler.stats()
                if proto._scheduler is not None else None,
            }
        stats["hub_queue"] = {"kem": hub._bkem.stats(), "sig": hub._bsig.stats()}
        stats["client_queue"] = {"kem": proto._bkem.stats(),
                                 "sig": proto._bsig.stats()}
        if hub._bfused is not None:
            stats["hub_queue"]["fused"] = hub._bfused.stats()
        if proto._bfused is not None:
            stats["client_queue"]["fused"] = proto._bfused.stats()
        total_ops = fb_ops = 0
        for side in ("hub_queue", "client_queue"):
            for fam in stats[side].values():
                for q in fam.values():
                    total_ops += q["ops"]
                    fb_ops += q["fallback_ops"]
        stats["device_served_pct"] = round(
            100.0 * (total_ops - fb_ops) / total_ops, 1) if total_ops else None
        # the 0..1 gauge tooling gates on (bench.py --slo fails <0.9): the
        # r3 "silent CPU swarm" regression must be caught by the harness
        stats["device_served_fraction"] = round(
            (total_ops - fb_ops) / total_ops, 4) if total_ops else None
        stats["breaker_state"] = hub._queue_breaker.state if hub._queue_breaker else None
        stats["breaker_opens"] = hub._queue_breaker.opens if hub._queue_breaker else 0
        stats["breaker_closes"] = hub._queue_breaker.closes if hub._queue_breaker else 0
        # Measured dispatch trips (never inferred): breaker delta over the
        # measured window across both sides.  In slo mode the window holds
        # ONLY handshakes, so the per-handshake quotient is exact at
        # concurrency 1; the client-side histogram (initiator trips between
        # initiate and completion) rides along from the client stacks.
        stats["dispatch_trips"] = trips_delta
        if latencies:
            stats["trips_per_handshake"] = round(trips_delta / len(latencies), 2)
        client_trips = [
            int(sm._handshake_trips.last) for sm in clients
            if sm._handshake_trips.count and sm._handshake_trips.last is not None
        ]
        if client_trips:
            srt = sorted(client_trips)
            stats["initiator_trips_p50"] = srt[len(srt) // 2]
            stats["initiator_trips_max"] = srt[-1]
    if slo_report is not None:
        stats["slo"] = slo_report
    return stats


def snapshot_digest(snap: dict) -> dict:
    """Compact a ``global_snapshot()`` for committing: a storm creates one
    registry PER SESSION (``messaging:peer01234``, plus ``#N`` dedup
    suffixes), so the raw dump runs to ~240k lines of mostly-identical
    per-peer histogram buckets.  The digest groups registries by class
    (everything before ``:``), sums counters, folds gauges to
    min/mean/max over the non-null instances, and merges histograms to
    bucketless count/sum/p50/p99 ranges — a few hundred lines that still
    answer every question the committed artifact exists for (rates,
    tails, totals).  Pass ``--full-snapshots`` for the raw dump.
    """
    groups: dict[str, list[dict]] = {}
    for name, reg in snap.items():
        groups.setdefault(str(name).split(":", 1)[0].split("#", 1)[0],
                          []).append(reg)
    digest: dict[str, dict] = {"_digest": {
        "registries": len(snap),
        "groups": {k: len(v) for k, v in sorted(groups.items())},
    }}
    for key, regs in sorted(groups.items()):
        counters: dict[str, float] = {}
        gauges: dict[str, list[float]] = {}
        hists: dict[str, dict] = {}
        for reg in regs:
            for cname, val in (reg.get("counters") or {}).items():
                if isinstance(val, (int, float)):
                    counters[cname] = counters.get(cname, 0) + val
            for gname, val in (reg.get("gauges") or {}).items():
                if isinstance(val, (int, float)):
                    gauges.setdefault(gname, []).append(val)
            for hname, h in (reg.get("histograms") or {}).items():
                if not isinstance(h, dict):
                    continue
                agg = hists.setdefault(hname, {"count": 0, "sum": 0.0,
                                               "p50": [], "p99": []})
                agg["count"] += h.get("count") or 0
                agg["sum"] += h.get("sum") or 0.0
                for p in ("p50", "p99"):
                    if isinstance(h.get(p), (int, float)):
                        agg[p].append(h[p])
        digest[key] = {
            "instances": len(regs),
            "counters": dict(sorted(counters.items())),
            "gauges": {g: {"min": min(vs), "max": max(vs),
                           "mean": round(sum(vs) / len(vs), 6)}
                       for g, vs in sorted(gauges.items())},
            "histograms": {h: {"count": agg["count"],
                               "sum": round(agg["sum"], 6),
                               "p50_range": ([min(agg["p50"]), max(agg["p50"])]
                                             if agg["p50"] else None),
                               "p99_range": ([min(agg["p99"]), max(agg["p99"])]
                                             if agg["p99"] else None)}
                           for h, agg in sorted(hists.items())},
        }
    return digest


#: process-wide default for ``write_obs_artifacts`` (set_full_snapshots);
#: lets bench.py's many mode functions honor ONE --full-snapshots flag
#: without threading it through every signature
_FULL_SNAPSHOTS = False


def set_full_snapshots(value: bool) -> None:
    global _FULL_SNAPSHOTS
    _FULL_SNAPSHOTS = bool(value)


def write_obs_artifacts(stats: dict, out_dir: str | Path,
                        stem: str = "swarm",
                        full_snapshots: bool | None = None) -> dict:
    """Attach the run's observability artifacts to its JSON output
    (bench_results/): a chrome://tracing trace-event file of the recorded
    spans, the MERGED multi-node flame graph (one process lane per node,
    flow arrows on the propagated cross-peer parent edges —
    tools/trace_merge.py), and a metrics snapshot of every live registry
    — digested by :func:`snapshot_digest` unless ``full_snapshots``.
    Returns the paths added to ``stats``.  CI uploads these next to the
    qrflow SARIF.
    """
    from quantum_resistant_p2p_tpu.obs import metrics as obs_metrics
    from quantum_resistant_p2p_tpu.obs import trace as obs_trace
    from tools import trace_merge

    out = Path(out_dir)
    out.mkdir(parents=True, exist_ok=True)
    records = obs_trace.TRACER.snapshot()
    trace_path = out / f"{stem}_trace_events.json"
    trace_path.write_text(json.dumps(obs_trace.to_chrome_trace(records)))
    # every node in this process recorded into ONE tracer; the records'
    # per-span node attribution is what the merge groups lanes by
    merged = trace_merge.merge([obs_trace.span_dump(records=records)])
    merged_path = out / f"{stem}_merged_trace.json"
    merged_path.write_text(json.dumps(merged))
    metrics_path = out / f"{stem}_metrics_snapshot.json"
    if full_snapshots is None:
        full_snapshots = _FULL_SNAPSHOTS
    snap = obs_metrics.global_snapshot()
    if not full_snapshots:
        snap = snapshot_digest(snap)
    metrics_path.write_text(json.dumps(snap, indent=2, default=str))
    stats["obs"] = {
        "spans_recorded": len(records),
        "trace_events_file": str(trace_path),
        "merged_trace_file": str(merged_path),
        "merged_nodes": merged["otherData"]["merged_nodes"],
        "cross_node_edges": merged["otherData"]["cross_node_edges"],
        "metrics_snapshot_file": str(metrics_path),
        "metrics_snapshot_mode": "full" if full_snapshots else "digest",
    }
    return stats["obs"]


# -- storm workload (ISSUE 8: the sustained-traffic serving tier) -------------
#
# The swarm bench above measures a fixed wave of handshakes; the STORM mode
# measures the GATEWAY under sustained concurrent load: thousands of live
# sessions arriving at a configurable rate, holding their connections,
# mixing re-keys and bulk traffic, and churning — driven through the real
# net/p2p_node TCP transport and the full SecureMessaging protocol engine
# (admission control, priority lanes, and the batch autotuner all live).
#
# Crypto providers: ``--providers stdlib`` (the default for storms) runs
# hash-based toy KEM/SIG/AEAD — the same pattern the faults/scheduler test
# suites use — so the storm measures the SERVING LOOP (transport, protocol,
# queues, batching, admission) rather than raw crypto throughput, and runs
# on images without the OpenSSL wheel.  ``--providers real`` drives
# ML-KEM-768 + ML-DSA-65 through the same storm for hardware environments.
# The emitted JSON carries the provider set honestly.


def _percentile(sorted_vals: list, p: float):
    if not sorted_vals:
        return None
    return round(
        sorted_vals[min(len(sorted_vals) - 1,
                        max(0, int(len(sorted_vals) * p / 100.0)))], 4)


async def run_storm(sessions: int = 1000, providers: str = "stdlib",
                    arrival_rate: float = 0.0, concurrency: int = 512,
                    msgs_per_session: int = 2, rekey_every: int = 0,
                    churn_fraction: float = 0.0, seed: int = 0,
                    max_batch: int = 4096, max_wait_ms: float = 3.0,
                    autotune: bool = True, hub_max_peers: int = 0,
                    handshake_budget: int = 0, bulk_lane_capacity: int = 0,
                    shard_devices: int = 0, ke_timeout: float = 120.0,
                    prewarm: bool = True, prewarm_cap: int = 256,
                    aead_mode: str = "storm", payload_bytes: int = 0,
                    resume_mix: bool = False,
                    fault_rules=None) -> dict:
    """Sustained-traffic storm: ``sessions`` live peers through one hub.

    Each session (seeded, reproducible): dial (busy-shed retries included)
    -> authenticated handshake -> ``msgs_per_session`` bulk messages, with
    a forced RE-KEY every ``rekey_every`` messages and, with probability
    ``churn_fraction``, one churn cycle (drop the TCP session, redial,
    re-handshake).  ``arrival_rate`` > 0 paces session starts (sessions/s,
    uniform); 0 launches everything behind the ``concurrency`` gate.

    ``aead_mode`` picks the bulk-message AEAD (the ``--bulk-mix``
    comparison axis, docs/gateway.md "Bulk-heavy storms"):

    * ``storm`` — the stdlib toy AEAD (historical default);
    * ``chacha`` — real ChaCha20-Poly1305 through the BATCHED device
      facade (core/chacha_pallas.py via provider/batched.BatchedAEAD);
    * ``chacha-scalar`` — the same algorithm on the scalar per-message
      path (the baseline the >=5x bulk ratchet compares against).

    ``payload_bytes`` pads every bulk message's content up to that size
    (0 keeps the historical tiny payloads).  Per-message send latency
    (sign + seal + write) is measured and reported as p50/p99_msg_s.

    ``resume_mix`` (the ``--resume-mix`` ratchet, docs/protocol.md
    "Session resumption"): every session DROPS its TCP connection halfway
    through its workload, redials, and re-establishes — with a held
    resumption ticket that reconnect is a 1-RTT resume (no KEM, no
    signatures, no device dispatch) instead of a full handshake.  The
    report carries the resume rate, resume-vs-full latency split, and a
    sequential post-storm cost probe pinning the "resumes cost ~0
    device-seconds" claim (device trips + cost-ledger device seconds
    across N pure resume cycles).

    Returns one JSON-ready dict: handshakes/s, p50/p99 split by first
    handshake vs rekey lane, shed counters (connection / handshake /
    bulk), device_served_fraction, and the autotuner's decisions.
    ``fault_rules`` (faults/) arms a seeded chaos plan around the measured
    window — plan.injected rides along, byte-reproducible given the seed.
    """
    import random

    from quantum_resistant_p2p_tpu.app import messaging as _messaging
    from quantum_resistant_p2p_tpu.app.messaging import SecureMessaging
    from quantum_resistant_p2p_tpu.faults import FaultPlan
    from quantum_resistant_p2p_tpu.net.p2p_node import P2PNode
    from quantum_resistant_p2p_tpu.provider import get_kem, get_signature

    if providers == "stdlib":
        _register_storm_providers()
        kem_name, sig_name = "STORM-KEM", "STORM-SIG"
    else:
        kem_name, sig_name = "ML-KEM-768", "ML-DSA-65"
        from quantum_resistant_p2p_tpu.utils.benchmarking import (
            enable_compile_cache)

        enable_compile_cache()

    rng = random.Random(seed)
    if aead_mode == "storm":
        aead = _StormAEAD()
        batch_aead = False
    elif aead_mode in ("chacha", "chacha-scalar"):
        from quantum_resistant_p2p_tpu.provider import get_symmetric

        aead = get_symmetric("ChaCha20-Poly1305")
        batch_aead = aead_mode == "chacha"
    else:
        raise ValueError(f"unknown aead_mode {aead_mode!r}")
    # storm_env (fleet/stormlib.py — the same guard every fleet gateway
    # subprocess enters): raised fd limit + module-global protocol-timeout
    # save/restore.  Everything below also runs under one finally: an
    # exception escaping a session task (or Ctrl-C) must still close every
    # socket, and the env's own finally restores the timeout -- bench.py's
    # storm ratchet runs four storms in one process
    clients: list[SecureMessaging] = []
    hub_node = proto = None
    with _storm_env(ke_timeout, fd_need=4 * sessions + 64):
        try:
            gateway_kw = dict(
                use_batching=True, max_batch=max_batch, max_wait_ms=max_wait_ms,
                autotune=autotune, shard_devices=shard_devices,
                batch_aead=batch_aead,
            )
            hub_node = P2PNode(node_id="hub", host="127.0.0.1", port=0,
                               max_peers=hub_max_peers)
            await hub_node.start()
            hub = SecureMessaging(
                hub_node, kem=get_kem(kem_name, "tpu"), symmetric=aead,
                signature=get_signature(sig_name, "tpu"),
                max_inflight_handshakes=handshake_budget,
                bulk_lane_capacity=bulk_lane_capacity, **gateway_kw,
            )
            received = 0

            def on_msg(peer_id, message):
                nonlocal received
                if not message.is_system:
                    received += 1

            hub.register_message_listener(on_msg)

            # one shared client-side batching plane (the proto pattern above):
            # every client coalesces into the same queues / autotuner
            proto = SecureMessaging(
                P2PNode(node_id="proto", host="127.0.0.1", port=0),
                kem=get_kem(kem_name, "tpu"), symmetric=aead,
                signature=get_signature(sig_name, "tpu"), **gateway_kw,
            )
            await hub.wait_ready()
            await proto.wait_ready()

            if prewarm:
                # warm every pow2 flush bucket a live storm can hit (up to the
                # cap) on BOTH planes.  The AEAD facades additionally key
                # compiled programs on the (msg, aad) LENGTH buckets: point
                # their warm shapes at the bucket this storm's package size
                # actually lands in (b64 content + envelope + sig material)
                # before the sweep compiles them.
                aead_facades = ()
                if batch_aead and hub._baead is not None:
                    est = (4 * max(payload_bytes, 64)) // 3 + 640
                    shapes = ((hub._baead.device._msg_bucket(est), 256),)
                    hub._baead.warm_shapes = shapes
                    proto._baead.warm_shapes = shapes
                    aead_facades = (proto._baead, hub._baead)
                await _prewarm_facades(
                    (proto._bkem, proto._bsig, hub._bkem, hub._bsig,
                     proto._bfused, hub._bfused) + aead_facades,
                    min(max_batch, max(concurrency, 1), prewarm_cap))

            n_keys = sessions
            kp_pks, kp_sks = proto.signature.generate_keypair_batch(n_keys)

            first_lat: list[float] = []
            rekey_lat: list[float] = []
            msg_lat: list[float] = []
            resume_lat: list[float] = []
            churns = rekeys = 0
            resumes_done = resume_fulls = 0
            failures = 0
            sem = asyncio.Semaphore(concurrency)

            def make_client(i: int) -> SecureMessaging:
                node = P2PNode(node_id=f"peer{i:05d}", host="127.0.0.1", port=0)
                sm = SecureMessaging(
                    node, kem=proto.kem, symmetric=proto.symmetric,
                    signature=proto.signature,
                    sig_keypair=(bytes(kp_pks[i]), bytes(kp_sks[i])))
                sm._bkem, sm._bsig, sm._bfused = proto._bkem, proto._bsig, proto._bfused
                sm._baead = proto._baead  # the shared data plane too
                sm.use_batching = True
                clients.append(sm)
                return sm

            def _payload(i: int, k: int) -> bytes:
                base = b"storm payload %d/%d" % (i, k)
                return (base.ljust(payload_bytes, b"x")
                        if payload_bytes else base)

            async def handshake(sm, bucket: list[float]) -> bool:
                nonlocal failures
                t0 = time.perf_counter()
                ok = await sm.initiate_key_exchange("hub")
                bucket.append(time.perf_counter() - t0)
                if not ok:
                    failures += 1
                return ok

            async def resume_cycle(sm) -> bool:
                """One resume-mix reconnect: drop the TCP session, redial,
                re-establish (a held ticket makes it a 1-RTT resume; any
                failure falls back to the full handshake inside
                initiate_key_exchange — never a stall)."""
                nonlocal resumes_done, resume_fulls, failures
                await sm.node.disconnect_from_peer("hub")
                if await sm.node.connect_to_peer("127.0.0.1", hub_node.port,
                                                 retries=4) != "hub":
                    failures += 1
                    return False
                r0 = sm._ctr_resumes_used.value
                rt0 = time.perf_counter()
                ok = await sm.initiate_key_exchange("hub")
                took = time.perf_counter() - rt0
                if not ok:
                    failures += 1
                    return False
                if sm._ctr_resumes_used.value > r0:
                    resumes_done += 1
                    # only ACTUAL resumes feed the resume-latency split —
                    # a fallback's full-handshake time in this bucket
                    # would let the "resumes are cheap" gate compare full
                    # handshakes to full handshakes
                    resume_lat.append(took)
                else:
                    resume_fulls += 1
                return True

            async def one_session(i: int, start_at: float, t_origin: float,
                                  srng: random.Random) -> None:
                nonlocal churns, rekeys, failures
                delay = start_at - (time.perf_counter() - t_origin)
                if delay > 0:
                    await asyncio.sleep(delay)
                async with sem:
                    sm = make_client(i)
                    if await sm.node.connect_to_peer("127.0.0.1", hub_node.port,
                                                     retries=4) != "hub":
                        failures += 1
                        return
                    if not await handshake(sm, first_lat):
                        return
                    for k in range(msgs_per_session):
                        mt0 = time.perf_counter()
                        await sm.send_message("hub", _payload(i, k))
                        msg_lat.append(time.perf_counter() - mt0)
                        if (resume_mix
                                and k + 1 == max(1, msgs_per_session // 2)):
                            # mid-workload reconnect: the resume fast path
                            if not await resume_cycle(sm):
                                return
                        if rekey_every and (k + 1) % rekey_every == 0:
                            # forced re-key: drop the session key and run the
                            # 5-message handshake again — rides the REKEY lane on
                            # both sides (sm and hub have completed a session)
                            sm.shared_keys.pop("hub", None)
                            sm.ke_state["hub"] = _messaging.KeyExchangeState.NONE
                            rekeys += 1
                            if not await handshake(sm, rekey_lat):
                                return
                    if churn_fraction and srng.random() < churn_fraction:
                        # churn: drop the TCP session entirely, redial, re-key
                        await sm.node.disconnect_from_peer("hub")
                        churns += 1
                        if await sm.node.connect_to_peer("127.0.0.1", hub_node.port,
                                                         retries=4) == "hub":
                            await handshake(sm, rekey_lat)
                        else:
                            failures += 1

            # seeded arrival schedule + per-session RNGs: the offered-load trace
            # is a pure function of (seed, sessions, arrival_rate)
            offsets = []
            t = 0.0
            for _ in range(sessions):
                if arrival_rate > 0:
                    t += rng.uniform(0.0, 2.0 / arrival_rate)  # mean 1/rate
                offsets.append(t)
            session_rngs = [random.Random(rng.getrandbits(64)) for _ in range(sessions)]

            plan = FaultPlan(seed, list(fault_rules)) if fault_rules else None
            ctx = plan.activate() if plan is not None else None
            if ctx is not None:
                ctx.__enter__()
            t_origin = time.perf_counter()
            try:
                await asyncio.gather(*(
                    one_session(i, offsets[i], t_origin, session_rngs[i])
                    for i in range(sessions)))
            finally:
                if ctx is not None:
                    ctx.__exit__(None, None, None)
            elapsed = time.perf_counter() - t_origin

            resume_probe = None
            if resume_mix and clients:
                # sequential post-storm cost probe: N pure resume cycles on
                # one client, device trips + cost-ledger device seconds
                # sampled around them — the committed artifact's evidence
                # that resumes cost ~0 device-seconds (no KEM, no sigs, no
                # AEAD dispatch rides the abbreviated exchange)
                sm = clients[0]
                trips0 = hub._trips_now() + proto._trips_now()
                dsec0 = ((hub.cost.totals().get("device_seconds") or 0.0)
                         + (proto.cost.totals().get("device_seconds") or 0.0))
                probe_ok = 0
                for _ in range(8):
                    r0 = sm._ctr_resumes_used.value
                    await sm.node.disconnect_from_peer("hub")
                    if await sm.node.connect_to_peer(
                            "127.0.0.1", hub_node.port, retries=4) != "hub":
                        break
                    if not await sm.initiate_key_exchange("hub"):
                        break
                    if sm._ctr_resumes_used.value > r0:
                        probe_ok += 1
                resume_probe = {
                    "resumes": probe_ok,
                    "device_trips": (hub._trips_now() + proto._trips_now()
                                     - trips0),
                    "device_seconds": round(
                        (hub.cost.totals().get("device_seconds") or 0.0)
                        + (proto.cost.totals().get("device_seconds") or 0.0)
                        - dsec0, 6),
                }

            hub_metrics = hub.metrics()
            proto_metrics = proto.metrics()

        finally:
            for sm in clients:
                await sm.node.stop()
            if hub_node is not None:
                await hub_node.stop()
            if proto is not None:
                await proto.node.stop()

    total_hs = len(first_lat) + len(rekey_lat)
    total_ops = fb_ops = 0
    for m in (hub_metrics, proto_metrics):
        for fam in ("kem_queue", "sig_queue", "fused_queue", "aead_queue"):
            for q in m.get(fam, {}).values():
                total_ops += q["ops"]
                fb_ops += q["fallback_ops"]
    f_sorted, r_sorted = sorted(first_lat), sorted(rekey_lat)
    m_sorted = sorted(msg_lat)
    client_busy = sum(sm.node.busy_rejects for sm in clients)
    out = {
        "workload": "storm",
        "sessions": sessions,
        "providers": ("stdlib-toy (serving-loop workload; PQ crypto "
                      "benched by --slo/raw-ops)" if providers == "stdlib"
                      else f"{kem_name}+{sig_name}"),
        "aead": aead.name,
        "aead_mode": aead_mode,
        "batch_aead": batch_aead,
        "payload_bytes": payload_bytes,
        "seed": seed,
        "arrival_rate": arrival_rate,
        "concurrency": concurrency,
        "msgs_per_session": msgs_per_session,
        "rekey_every": rekey_every,
        "churn_fraction": churn_fraction,
        "autotune": autotune,
        "shard_devices": shard_devices,
        "elapsed_s": round(elapsed, 3),
        "failures": failures,
        "handshakes": total_hs,
        "handshakes_per_s": round(total_hs / elapsed, 2) if elapsed else None,
        "msgs_received": received,
        "msgs_per_s": round(received / elapsed, 2) if elapsed else None,
        # per-message SEND latency (sign + seal + frame write): the bulk
        # p99 bound the --bulk-mix ratchet gates on
        "p50_msg_s": _percentile(m_sorted, 50),
        "p99_msg_s": _percentile(m_sorted, 99),
        "p50_handshake_s": _percentile(f_sorted, 50),
        "p99_handshake_s": _percentile(f_sorted, 99),
        "rekeys": rekeys,
        "p50_rekey_s": _percentile(r_sorted, 50),
        "p99_rekey_s": _percentile(r_sorted, 99),
        "churns": churns,
        # the resume-mix split (docs/protocol.md "Session resumption"):
        # reconnects that resumed via ticket vs full-handshake fallbacks,
        # their latency, and the post-storm device-cost probe
        "resume_mix": resume_mix,
        "resumed_reconnects": resumes_done,
        "full_handshake_reconnects": resume_fulls,
        "ticket_resume_rate": (
            round(resumes_done / (resumes_done + resume_fulls), 4)
            if (resumes_done + resume_fulls) else None),
        "p50_resume_s": _percentile(sorted(resume_lat), 50),
        "p99_resume_s": _percentile(sorted(resume_lat), 99),
        "resume_cost_probe": resume_probe,
        "resumption_hub": hub_metrics.get("resumption"),
        "device_served_fraction": (
            round((total_ops - fb_ops) / total_ops, 4) if total_ops else None),
        "sheds": {
            "connection": hub_node.sheds,
            "client_busy_rejects": client_busy,
            "handshake": hub_metrics["gateway"]["handshake_sheds"],
            "bulk_hub": hub_metrics["gateway"]["bulk_sheds"],
            "bulk_clients": sum(
                sm._ctr_bulk_sheds.value for sm in clients) if clients else 0,
        },
        "gateway_hub": {
            k: hub_metrics["gateway"][k]
            for k in ("max_peers", "handshake_budget", "handshake_sheds")},
        "autotune_hub": hub_metrics["gateway"]["autotune"],
        "autotune_clients": proto_metrics["gateway"]["autotune"],
        # the data plane's seal/open queues (None on scalar-AEAD storms)
        "aead_queue": {"hub": hub_metrics.get("aead_queue"),
                       "client_plane": proto_metrics.get("aead_queue")},
        # burn-rate health of both planes at storm end (obs/slo.py):
        # the consumer-grade signal the raw shed/served counters feed
        "slo": {"hub": hub_metrics["slo"],
                "client_plane": proto_metrics["slo"]},
        # the device-cost ledgers at storm end (obs/cost.py): padding
        # waste, compile attribution, device seconds, opcache windows —
        # bench.py writes this as {mode}_cost_snapshot.json
        "cost": {"hub": hub_metrics["cost"],
                 "client_plane": proto_metrics["cost"]},
    }
    if plan is not None:
        out["chaos"] = {
            "seed": plan.seed,
            "injected": len(plan.injected),
            "first_injected": plan.injected[:8],
        }
    return out


def _setup_emulated_devices(n: int) -> None:
    """Force an n-device virtual CPU platform (tests/conftest.py's trick)
    for multichip runs on single-accelerator hosts.  Must run before the
    first jax BACKEND initialization (import alone is fine — this image's
    TPU bootstrap imports jax at interpreter start)."""
    import os

    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + f" --xla_force_host_platform_device_count={n}"
        ).strip()
    import jax

    jax.config.update("jax_platforms", "cpu")


def run_multichip(shard_counts=(1, 2, 4, 8), batch: int = 4096,
                  hs_peers: int = 32, hs_concurrency: int = 8,
                  hs_warmup: int = 8, emulate: int = 0) -> dict:
    """Measure 1→N-chip scaling of BOTH production paths and return the
    scaling curve (the real MULTICHIP bench — earlier rounds' files only
    recorded reachability).

    * **encaps/s** — the large-batch raw-ops path: one ``batch``-row
      ML-KEM-768 encapsulation program with the batch axis GSPMD-sharded
      across an n-device mesh (``parallel.mesh``), device-resident
      operands, forced-readback honest timing (utils/benchmarking — the
      same methodology as the single-chip headline in bench.py).
    * **warm handshakes/s** — the latency path: the swarm bench with the
      queue flushes placed across ``shard_devices=n`` scheduler shards
      (set ``hs_peers=0`` to skip; it costs one prewarm compile sweep per
      shard count).
    """
    if emulate:
        _setup_emulated_devices(emulate)
    import jax
    import numpy as np
    from jax.sharding import NamedSharding, PartitionSpec as P

    from quantum_resistant_p2p_tpu.kem import mlkem
    from quantum_resistant_p2p_tpu.parallel.mesh import BATCH_AXIS, make_mesh
    from quantum_resistant_p2p_tpu.utils.benchmarking import (
        enable_compile_cache, sync, timeit)

    enable_compile_cache()
    n_devices = len(jax.devices())
    counts = sorted({c for c in shard_counts if 1 <= c <= n_devices} | {1})
    dropped = sorted(set(shard_counts) - set(counts))
    if dropped:
        print(f"multichip: only {n_devices} device(s) visible; "
              f"skipping shard counts {dropped}", file=sys.stderr)

    _, enc, _ = mlkem.get("ML-KEM-768")
    rng = np.random.default_rng(0)
    # one keypair reused across rows (the swarm-hot-peer shape); encaps
    # math is row-independent so scaling is not key-bound
    from quantum_resistant_p2p_tpu.provider import get_kem

    ek_row = get_kem("ML-KEM-768", "tpu").generate_keypair()[0]
    eks = np.broadcast_to(
        np.frombuffer(ek_row, np.uint8), (batch, len(ek_row))).copy()
    ms = rng.integers(0, 256, size=(batch, 32), dtype=np.uint8)

    shards: dict[str, dict] = {}
    for n in counts:
        mesh = make_mesh(n)
        sh = NamedSharding(mesh, P(BATCH_AXIS))
        # device-resident sharded operands: the timed region measures the
        # chips, not the host link (raw-ops methodology, bench.py)
        ek_d = jax.device_put(eks, sh)
        m_d = jax.device_put(ms, sh)
        sync((ek_d, m_d))
        encaps_per_s = batch / timeit(enc, ek_d, m_d)
        entry: dict = {
            "n_shards": n,
            "encaps_per_s": round(encaps_per_s, 1),
            "encaps_batch": batch,
            "rows_per_device": batch // n,
        }
        if hs_peers:
            hs = asyncio.run(run_swarm(
                hs_peers, backend="tpu", use_batching=True, max_batch=4096,
                max_wait_ms=2.0, concurrency=hs_concurrency, warmup=hs_warmup,
                prewarm=True, shard_devices=n,
            ))
            entry["handshakes_per_s"] = hs.get("handshakes_per_s")
            entry["p50_handshake_s"] = hs.get("p50_handshake_s")
            entry["device_served_fraction"] = hs.get("device_served_fraction")
            entry["failures"] = hs.get("failures")
        shards[str(n)] = entry

    base = shards["1"]["encaps_per_s"]
    for entry in shards.values():
        entry["encaps_speedup_vs_1"] = round(entry["encaps_per_s"] / base, 2)
        if entry.get("handshakes_per_s") and shards["1"].get("handshakes_per_s"):
            entry["handshakes_speedup_vs_1"] = round(
                entry["handshakes_per_s"] / shards["1"]["handshakes_per_s"], 2)
    top = str(max(counts))
    return {
        "metric": f"multichip_mlkem768_encaps_batch{batch}_scaling",
        "unit": "encaps/s",
        "n_devices": n_devices,
        # honesty marker: an emulated run measures the GSPMD partitioning
        # on virtual CPU devices, not real-ICI chip scaling
        "emulated_devices": emulate or None,
        "platform": jax.devices()[0].platform,
        "shard_counts": counts,
        "value": shards[top]["encaps_per_s"],
        "value_at_1": base,
        "speedup_max_shards": shards[top]["encaps_speedup_vs_1"],
        "shards": shards,
        "ok": True,
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--peers", type=int, default=1000)
    ap.add_argument("--backend", default="tpu", choices=("cpu", "tpu", "auto"))
    ap.add_argument("--batch", action="store_true", default=True)
    ap.add_argument("--no-batch", dest="batch", action="store_false")
    ap.add_argument("--max-batch", type=int, default=4096)
    ap.add_argument("--max-wait-ms", type=float, default=3.0)
    ap.add_argument("--concurrency", type=int, default=256,
                    help="simultaneous in-flight handshakes")
    ap.add_argument("--warmup", type=int, default=32,
                    help="untimed warmup handshakes (compile the size buckets)")
    ap.add_argument("--ke-timeout", type=float, default=180.0)
    ap.add_argument("--batch-floor", type=int, default=1,
                    help="pad device flushes up to this pow2 bucket "
                         "(collapses the bucket space so --prewarm covers it)")
    ap.add_argument("--shard-devices", type=int, default=0,
                    help="place queue flushes across this many scheduler "
                         "shards (provider/scheduler.py; 0 = one shard)")
    ap.add_argument("--prewarm", action="store_true",
                    help="compile every reachable flush bucket on hub+client "
                         "facades before the measured window")
    ap.add_argument("--slo", action="store_true",
                    help="single-handshake SLO probe: sequential handshakes "
                         "only, with per-handshake dispatch-trip accounting "
                         "(forces --concurrency 1)")
    ap.add_argument("--obs-dir", default="bench_results",
                    help="directory for the trace-event, merged multi-node "
                         "trace, and metrics-snapshot artifacts (slo/storm "
                         "modes; '' disables)")
    ap.add_argument("--full-snapshots", action="store_true",
                    help="write the RAW per-registry metrics snapshot "
                         "(~MBs for a storm: one registry per session) "
                         "instead of the compact committed digest")
    ap.add_argument("--storm", action="store_true",
                    help="sustained-traffic storm: --peers concurrent live "
                         "sessions with arrival pacing, rekey/bulk mix and "
                         "churn through the gateway (admission control, "
                         "priority lanes, batch autotuner)")
    ap.add_argument("--fleet", type=int, default=0,
                    help="with --storm: drive the sessions through an "
                         "N-gateway-PROCESS fleet behind the consistent-hash "
                         "router (fleet/) instead of one in-process hub")
    ap.add_argument("--spawn", default="process", choices=("process", "task"),
                    help="fleet gateway isolation: real subprocesses "
                         "(default) or in-process asyncio tasks (CI images "
                         "without subprocess headroom; same control protocol)")
    ap.add_argument("--chaos-kill", default="",
                    help="fleet chaos: SIGKILL this gateway id mid-storm via "
                         "the seeded fault plan's process scope (e.g. 'gw1')")
    ap.add_argument("--kill-tick", type=int, default=8,
                    help="health tick the --chaos-kill rule fires on")
    ap.add_argument("--per-gateway-max-peers", type=int, default=0,
                    help="fleet: per-gateway connection budget; the fleet "
                         "admission budget is the sum over CLOSED members "
                         "(0 = unlimited)")
    ap.add_argument("--providers", default="stdlib",
                    choices=("stdlib", "real"),
                    help="storm crypto: stdlib toys (serving-loop workload, "
                         "wheel-less images) or ML-KEM-768+ML-DSA-65")
    ap.add_argument("--arrival-rate", type=float, default=0.0,
                    help="storm session starts per second (0 = all at once "
                         "behind --concurrency)")
    ap.add_argument("--msgs-per-session", type=int, default=2)
    ap.add_argument("--bulk-mix", type=int, default=0,
                    help="storm: bulk-heavy profile — this many bulk "
                         "messages per session (overrides "
                         "--msgs-per-session) with 2 KiB payloads unless "
                         "--payload-bytes says otherwise")
    ap.add_argument("--aead", default="storm",
                    choices=("storm", "chacha", "chacha-scalar"),
                    help="storm bulk AEAD: stdlib toy (default), batched "
                         "device ChaCha20-Poly1305, or its scalar baseline")
    ap.add_argument("--payload-bytes", type=int, default=0,
                    help="pad bulk message contents to this size "
                         "(0 = tiny legacy payloads; --bulk-mix defaults "
                         "this to 2048)")
    ap.add_argument("--resume-mix", action="store_true",
                    help="storm mode: every session drops its TCP "
                         "connection mid-workload and re-establishes via "
                         "its resumption ticket (1-RTT resume, no KEM/sig) "
                         "— reports the resume rate + cost probe")
    ap.add_argument("--rekey-every", type=int, default=0,
                    help="force a re-key every N bulk messages per session")
    ap.add_argument("--churn", type=float, default=0.0,
                    help="per-session probability of one churn cycle "
                         "(drop TCP, redial, re-key)")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--no-autotune", dest="autotune", action="store_false",
                    default=True, help="storm: pin the static flush policy")
    ap.add_argument("--hub-max-peers", type=int, default=0)
    ap.add_argument("--handshake-budget", type=int, default=0)
    ap.add_argument("--bulk-lane-capacity", type=int, default=0)
    args = ap.parse_args(argv)
    if args.storm and args.fleet:
        from quantum_resistant_p2p_tpu.fleet.storm import (
            default_kill_rules, run_fleet_storm, write_fleet_artifacts)

        rules = (default_kill_rules(args.chaos_kill, args.kill_tick)
                 if args.chaos_kill else None)
        stats = asyncio.run(run_fleet_storm(
            args.peers, gateways=args.fleet, providers=args.providers,
            seed=args.seed, arrival_rate=args.arrival_rate,
            concurrency=args.concurrency,
            msgs_per_session=args.msgs_per_session, spawn=args.spawn,
            per_gateway_max_peers=args.per_gateway_max_peers,
            handshake_budget=args.handshake_budget,
            max_batch=args.max_batch, max_wait_ms=args.max_wait_ms,
            autotune=args.autotune, ke_timeout=args.ke_timeout,
            fault_rules=rules,
        ))
        if args.obs_dir:
            write_obs_artifacts(stats, args.obs_dir, stem="fleet_storm",
                                full_snapshots=args.full_snapshots)
            write_fleet_artifacts(stats, args.obs_dir)
        print(json.dumps(stats))
        # the fleet chaos currency: no ESTABLISHED session may be lost —
        # un-established failures under a kill are the bounded burst the
        # report carries honestly
        return 0 if stats["lost_established_sessions"] == 0 else 1
    if args.storm:
        msgs = args.bulk_mix or args.msgs_per_session
        payload = args.payload_bytes or (2048 if args.bulk_mix else 0)
        stats = asyncio.run(run_storm(
            args.peers, providers=args.providers,
            arrival_rate=args.arrival_rate, concurrency=args.concurrency,
            msgs_per_session=msgs,
            rekey_every=args.rekey_every, churn_fraction=args.churn,
            seed=args.seed, max_batch=args.max_batch,
            max_wait_ms=args.max_wait_ms, autotune=args.autotune,
            hub_max_peers=args.hub_max_peers,
            handshake_budget=args.handshake_budget,
            bulk_lane_capacity=args.bulk_lane_capacity,
            shard_devices=args.shard_devices, ke_timeout=args.ke_timeout,
            aead_mode=args.aead, payload_bytes=payload,
            resume_mix=args.resume_mix,
        ))
        if args.obs_dir:
            write_obs_artifacts(stats, args.obs_dir, stem="storm",
                                full_snapshots=args.full_snapshots)
        print(json.dumps(stats))
        return 0 if stats["failures"] == 0 else 1
    if args.slo:
        args.concurrency = 1
    stats = asyncio.run(
        run_swarm(args.peers, args.backend, args.batch, args.max_batch,
                  args.max_wait_ms, args.concurrency, args.warmup,
                  args.ke_timeout, args.batch_floor, args.prewarm, args.slo,
                  args.shard_devices)
    )
    if args.slo and args.obs_dir:
        write_obs_artifacts(stats, args.obs_dir,
                            full_snapshots=args.full_snapshots)
    print(json.dumps(stats))
    return 0 if stats["failures"] == 0 else 1


if __name__ == "__main__":
    sys.exit(main())
