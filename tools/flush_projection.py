"""Per-flush decomposition + local-chip projection (VERDICT r3 item 1).

The swarm's measured per-flush dispatch walls on this environment include
the remote-TPU tunnel.  This tool decomposes one flush of each handshake op
at the swarm's bucket size into:

  host_pack_ms    — np.stack/pad of the operand rows (pure host)
  wall_ms         — the full batch-fn wall with HOST operands (what a live
                    flush pays here: pack + h2d transfer + compute + d2h)
  device_ms       — the same dispatch with DEVICE-RESIDENT operands and a
                    host readback (compute + d2h of results)
  tunnel_ms       — wall - device - pack (the h2d share of the tunnel)

and projects the local-chip flush wall as host_pack + device_ms + pcie_ms,
where pcie_ms is operand_bytes / 8 GB/s (a conservative figure for a
single-chip host link; the tunnel here moves ~0.4-2.2 MB/s).

Usage: python -m tools.flush_projection [--bucket 128]
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

import numpy as np  # noqa: E402

PCIE_BYTES_PER_S = 8e9


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--bucket", type=int, default=128)
    ap.add_argument("--out", default="bench_results/r4_flush_projection.json")
    args = ap.parse_args(argv)
    n = args.bucket

    from quantum_resistant_p2p_tpu.utils.benchmarking import (
        enable_compile_cache, timeit,
    )

    enable_compile_cache()
    import jax

    from quantum_resistant_p2p_tpu.provider.registry import get_kem, get_signature

    kem = get_kem("ML-KEM-768", "tpu")
    sig = get_signature("ML-DSA-65", "tpu")
    rng = np.random.default_rng(5)

    pks, sks = (np.asarray(a) for a in kem.generate_keypair_batch(n))
    cts, _ = (np.asarray(a) for a in kem.encapsulate_batch(pks))
    spk, ssk = sig.generate_keypair()
    sks_sig = np.stack([np.frombuffer(ssk, np.uint8)] * n)
    pks_sig = np.stack([np.frombuffer(spk, np.uint8)] * n)
    msgs = [b"m%05d" % i for i in range(n)]
    sigs = sig.sign_batch(sks_sig, msgs)

    # host packing cost: what the batch fns do before dispatch
    rows = [bytes(pk) for pk in pks]

    def pack():
        return np.stack([np.frombuffer(r, np.uint8) for r in rows])

    pack_ms = 1e3 * timeit(pack)

    # device-resident variants for sign/verify: the underlying jitted
    # kernels directly.  mu hashing (SHAKE256 of tr||M' per row, host-side
    # in sign_batch/verify_batch) is NOT separately attributed: it lands in
    # the tunnel_ms residual, slightly overstating it — sub-ms at this
    # bucket and message size, and a local chip pays it too, so the local
    # projection is marginally optimistic on that component.
    from quantum_resistant_p2p_tpu.sig import mldsa

    _, sign_mu, verify_mu = mldsa.get("ML-DSA-65")
    mus = jax.device_put(rng.integers(0, 256, (n, 64), np.uint8))
    rnds = jax.device_put(rng.integers(0, 256, (n, 32), np.uint8))
    sksd = jax.device_put(sks_sig)
    pksd = jax.device_put(pks_sig)
    sg, _dn = sign_mu(sksd, mus, rnds)
    sgd = jax.device_put(np.asarray(sg))
    pksdev = jax.device_put(pks)
    sksdev, ctsdev = jax.device_put(sks), jax.device_put(cts)

    # NOTE keygen: it has no host operands, so its "device" variant is the
    # same call as the wall — the decomposition is vacuous there and the
    # result is flagged not_decomposed (its device_ms still contains the
    # full result d2h through this environment's tunnel; the KEM rows are
    # conservative upper bounds for a local chip for the same reason).
    ops = {
        "keygen": dict(
            host=lambda: kem.generate_keypair_batch(n),
            dev=lambda: kem.generate_keypair_batch(n),
            n_arrays=0, operand_bytes=0, not_decomposed=True,
        ),
        "encaps": dict(
            host=lambda: kem.encapsulate_batch(pks),
            dev=lambda: kem.encapsulate_batch(pksdev),
            n_arrays=1, operand_bytes=pks.nbytes,
        ),
        "decaps": dict(
            host=lambda: kem.decapsulate_batch(sks, cts),
            dev=lambda: kem.decapsulate_batch(sksdev, ctsdev),
            n_arrays=2, operand_bytes=sks.nbytes + cts.nbytes,
        ),
        "sign": dict(
            host=lambda: sig.sign_batch(sks_sig, msgs),
            dev=lambda: sign_mu(sksd, mus, rnds),
            n_arrays=1, operand_bytes=sks_sig.nbytes,
        ),
        "verify": dict(
            host=lambda: sig.verify_batch(pks_sig, msgs, sigs),
            dev=lambda: verify_mu(pksd, mus, sgd),
            n_arrays=1,
            operand_bytes=pks_sig.nbytes + sum(len(s) for s in sigs),
        ),
    }

    out = {"bucket": n, "host_pack_ms_per_array": round(pack_ms, 2), "ops": {}}
    for name, spec in ops.items():
        spec["host"]()  # warm
        wall = 1e3 * timeit(spec["host"])
        spec["dev"]()
        device = 1e3 * timeit(spec["dev"])
        hostpack = pack_ms * spec["n_arrays"]
        tunnel = max(0.0, wall - device - hostpack)
        pcie = 1e3 * spec["operand_bytes"] / PCIE_BYTES_PER_S
        local = hostpack + device + pcie
        out["ops"][name] = {
            "wall_ms": round(wall, 1),
            "host_pack_ms": round(hostpack, 2),
            "device_ms": round(device, 1),
            "tunnel_ms": round(tunnel, 1),
            "operand_bytes": spec["operand_bytes"],
            "pcie_ms_at_8GBps": round(pcie, 3),
            "local_chip_projection_ms": round(local, 1),
            "not_decomposed": bool(spec.get("not_decomposed", False)),
        }
        print(f"{name:7s} wall {wall:7.1f}  device {device:7.1f}  "
              f"pack {hostpack:5.2f}  tunnel {tunnel:7.1f}  "
              f"local-proj {local:7.1f} ms", flush=True)

    # project the swarm handshake: per-handshake op mix (swarm measurement:
    # 1 kg + 1 enc + 1 dec + 4 sign + 4 verify ~= 11013 ops / 1000
    # handshakes: 3 peer-side signs + the hub's ke_response sign, ditto
    # verifies) serialised on one device
    per_hs_ms = (
        out["ops"]["keygen"]["local_chip_projection_ms"]
        + out["ops"]["encaps"]["local_chip_projection_ms"]
        + out["ops"]["decaps"]["local_chip_projection_ms"]
        + 4 * out["ops"]["sign"]["local_chip_projection_ms"]
        + 4 * out["ops"]["verify"]["local_chip_projection_ms"]
    ) / n
    out["local_chip_handshakes_per_s_projection"] = round(1e3 / per_hs_ms, 1)
    Path(args.out).write_text(json.dumps(out, indent=1))
    print(json.dumps({
        "local_chip_handshakes_per_s_projection":
            out["local_chip_handshakes_per_s_projection"]
    }))
    return 0


if __name__ == "__main__":
    sys.exit(main())
