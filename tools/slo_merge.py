"""Merge N per-node ``slo_report.json`` dumps into ONE fleet SLO report.

The consumption-side twin of ``tools/trace_merge.py``: where trace_merge
joins N nodes' span dumps into one timeline, slo_merge folds N gateway
processes' SLO reports (``app.messaging.SecureMessaging.slo_report()``
documents, written by ``fleet/gateway.py`` on shutdown as
``<gateway>_slo_report.json``) into one fleet document via
:func:`obs.slo.merge_reports`:

* per-SLO **fleet totals and burn** — cumulative good/bad summed by spec
  NAME across nodes, the offline twin of the fleet router's live windowed
  engine (``fleet/manager.py`` sums the same probe totals from
  heartbeats);
* **worst-node attribution** — each merged SLO names the gateway with the
  highest fast-window burn, so a fleet-level budget burn points at the
  process eating it;
* the **alerting roll-up** — every node whose local engine had latched an
  alert at dump time.

The fleet storm (``tools/swarm_bench.py --storm --fleet N``) emits this
merge inline (``fleet_slo_report.json``); this CLI reproduces it from the
per-node files CI uploads, and accepts a directory (merging every
``*_slo_report.json`` inside — the fleet's ``report_dir`` layout).

Usage::

    python -m tools.slo_merge --out fleet_slo.json gw0_slo_report.json gw1_slo_report.json
    python -m tools.slo_merge --out fleet_slo.json bench_results/fleet_reports/
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path
from typing import Any

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

from quantum_resistant_p2p_tpu.obs.slo import merge_reports  # noqa: E402


def collect_paths(inputs: list[str | Path]) -> list[Path]:
    """Expand report files/directories into the per-node report list."""
    paths: list[Path] = []
    for raw in inputs:
        p = Path(raw)
        if p.is_dir():
            paths.extend(sorted(p.glob("*_slo_report.json")))
        else:
            paths.append(p)
    return paths


def merge_files(paths: list[str | Path]) -> dict[str, Any]:
    reports = []
    for p in collect_paths(paths):
        reports.append(json.loads(Path(p).read_text()))
    if not reports:
        raise ValueError("no slo_report.json inputs found")
    return merge_reports(reports)


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("reports", nargs="+",
                    help="per-node slo_report.json files, or directories "
                         "holding *_slo_report.json (a fleet report_dir)")
    ap.add_argument("--out", default="fleet_slo_report.json",
                    help="merged fleet report output path")
    args = ap.parse_args(argv)
    try:
        doc = merge_files(args.reports)
    except ValueError as e:
        print(f"slo_merge: {e}", file=sys.stderr)
        return 2
    Path(args.out).write_text(json.dumps(doc, indent=2, sort_keys=True) + "\n")
    worst = doc.get("worst_node") or "-"
    alerting = doc.get("alerting") or []
    print(f"merged {len(doc['nodes'])} node report(s) "
          f"({', '.join(doc['nodes'])}): {len(doc['slos'])} SLO(s), "
          f"worst node {worst}, "
          f"{len(alerting)} alerting -> {args.out}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
