"""In-context ML-DSA sign-attempt attribution via jitted PREFIX programs.

The round-3 breakdown timed each attempt stage STANDALONE and found they sum
to ~55 ms while the in-loop attempt costs ~155 ms at batch 8192 — and the
committed unroll experiment proved the gap is not the while_loop boundary.
Standalone timings overlap host/device work across timing reps, so they
under-attribute the serial chain.  This probe times CUMULATIVE PREFIXES of
the attempt pipeline (p0 = ExpandMask only, p1 = +NTT(y), ... p7 = full
attempt): each prefix is one jitted program on device-resident operands,
ended with a host readback, so the DELTAS between consecutive prefixes are
the true in-context marginal cost of each stage.

Usage: python -m tools.r4_sign_prefix_probe [--batch 8192] [--name ML-DSA-65]
"""

from __future__ import annotations

import argparse
import functools
import json
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

import numpy as np  # noqa: E402


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch", type=int, default=8192)
    ap.add_argument("--name", default="ML-DSA-65")
    ap.add_argument("--out", default="bench_results/r4_sign_prefix_breakdown.json")
    args = ap.parse_args(argv)

    from quantum_resistant_p2p_tpu.utils.benchmarking import (
        enable_compile_cache, timeit,
    )

    enable_compile_cache()
    import jax
    import jax.numpy as jnp

    from quantum_resistant_p2p_tpu.core import keccak
    from quantum_resistant_p2p_tpu.sig import mldsa as M

    p = M.PARAMS[args.name]
    B = args.batch
    rng = np.random.default_rng(7)

    # one key replicated across the batch (the swarm-hub shape); mu distinct
    kg, _, _ = M.get(args.name)
    _, sk1 = kg(rng.integers(0, 256, (1, 32), np.uint8))
    sk = jnp.broadcast_to(jnp.asarray(sk1)[0], (B, sk1.shape[-1]))
    mu = jax.device_put(rng.integers(0, 256, (B, 64), np.uint8))
    rnd = jax.device_put(rng.integers(0, 256, (B, 32), np.uint8))

    # hoisted per-key work (outside the rejection loop in sign_mu_rounds)
    @jax.jit
    def hoist(sk, mu, rnd):
        rho, cap_k, tr, s1, s2, t0 = M._unpack_sk(p, sk)
        a_hat = M.expand_a(p, rho)
        s1_hat, s2_hat, t0_hat = M.ntt(s1), M.ntt(s2), M.ntt(t0)
        rhopp = keccak.shake256(jnp.concatenate([cap_k, rnd, mu], axis=-1), 64)
        return a_hat, s1_hat, s2_hat, t0_hat, rhopp

    a_hat, s1_hat, s2_hat, t0_hat, rhopp = (
        jnp.asarray(x) for x in hoist(sk, mu, rnd)
    )
    kappa = jnp.zeros((B,), jnp.int32)
    batch = (B,)

    def prefix(stage: int):
        """Build the attempt pipeline up to `stage`; returns a jittable fn."""

        def fn(rhopp, kappa, mu, a_hat, s1_hat, s2_hat, t0_hat):
            y = M.expand_mask(p, rhopp, kappa)                        # p0
            if stage == 0:
                return y
            y_hat = M.ntt(y)                                          # p1
            if stage == 1:
                return y_hat
            w = M.ntt_inv(M._matvec(a_hat, y_hat))                    # p2
            if stage == 2:
                return w
            w1, _ = M.decompose(p, w)                                 # p3
            w1_enc = M.simple_bit_pack(w1, p.w1_bits).reshape(batch + (-1,))
            ctilde = keccak.shake256(
                jnp.concatenate([mu, w1_enc], axis=-1), p.ctilde_len
            )
            if stage == 3:
                return ctilde
            c_hat = M.ntt(M.sample_in_ball(p, ctilde))                # p4
            if stage == 4:
                return c_hat
            cs1 = M.ntt_inv(M.pw_mul(c_hat[..., None, :], s1_hat))    # p5
            z = (y + cs1) % M.Q
            ok = M._inf_norm(z, (-1, -2)) < p.gamma1 - p.beta
            if stage == 5:
                return z, ok
            cs2 = M.ntt_inv(M.pw_mul(c_hat[..., None, :], s2_hat))    # p6
            r_minus = (w - cs2) % M.Q
            _, r0 = M.decompose(p, r_minus)
            ok &= jnp.max(jnp.abs(r0), axis=(-1, -2)) < p.gamma2 - p.beta
            if stage == 6:
                return r_minus, ok
            ct0 = M.ntt_inv(M.pw_mul(c_hat[..., None, :], t0_hat))    # p7
            ok &= M._inf_norm(ct0, (-1, -2)) < p.gamma2
            h_arg = (M._center(r_minus) + M._center(ct0)) % M.Q
            hi_with = M.decompose(p, h_arg)[0]
            hi_base = M.decompose(p, r_minus)[0]
            h = (hi_with != hi_base).astype(jnp.int32)
            ok &= jnp.sum(h, axis=(-1, -2)) <= p.omega
            sigma = jnp.concatenate(
                [
                    ctilde,
                    M.bit_pack(z, p.gamma1, p.z_bits).reshape(batch + (-1,)),
                    M.hint_bit_pack(p, h),
                ],
                axis=-1,
            )
            return sigma, ok

        return jax.jit(fn)

    labels = [
        "p0_expand_mask", "p1_ntt_y", "p2_w_matvec_invntt",
        "p3_decompose_pack_ctilde", "p4_ball_ntt", "p5_cs1_z_check",
        "p6_cs2_r0_check", "p7_ct0_hint_pack_sigma",
    ]
    out = {"batch": B, "name": args.name, "cumulative_ms": {}, "delta_ms": {}}
    prev = 0.0
    for stage, lab in enumerate(labels):
        fn = prefix(stage)
        fn(rhopp, kappa, mu, a_hat, s1_hat, s2_hat, t0_hat)  # compile
        t = timeit(functools.partial(
            fn, rhopp, kappa, mu, a_hat, s1_hat, s2_hat, t0_hat
        ))
        ms = 1e3 * t
        out["cumulative_ms"][lab] = round(ms, 2)
        out["delta_ms"][lab] = round(ms - prev, 2)
        prev = ms
        print(f"{lab:28s} cum {ms:8.2f} ms   delta {out['delta_ms'][lab]:8.2f} ms",
              flush=True)

    Path(args.out).write_text(json.dumps(out, indent=1))
    print(json.dumps({"prefix_total_ms": out["cumulative_ms"][labels[-1]]}))
    return 0


if __name__ == "__main__":
    sys.exit(main())
