"""On-chip bit-exactness of the real ``pallas_call`` launchers vs the jnp path.

The CPU test suite exercises the Pallas kernel *bodies* eagerly
(tests/test_mlkem_pallas.py) because XLA-CPU cannot compile the unrolled
sponge graphs and interpret mode is as slow.  What that leaves untested is
the launcher plumbing itself — Mosaic compilation, sampler_call's
BlockSpec/grid setup, and the hi/lo word transport (advisor round-2
finding).  This tool runs every fused kernel through its real
``pallas_call`` on the TPU and compares bit-for-bit against the pure-jnp
formulations.

Run standalone on the chip (single TPU process rule applies):

    python -m tools.check_pallas_device

tests/test_pallas_device.py wraps the same checks, gated on a TPU backend.
"""

from __future__ import annotations

import os

# The jnp reference paths must be traced WITHOUT the pallas branch; the flag
# is read at trace time and cached by jit, so it must be set before import.
os.environ.setdefault("QRP2P_PALLAS", "0")

import numpy as np  # noqa: E402

B = 300  # deliberately not a multiple of the 1024-sponge tile


def check_sample_ntt() -> None:
    import jax.numpy as jnp

    from quantum_resistant_p2p_tpu.core import keccak
    from quantum_resistant_p2p_tpu.kem import mlkem, mlkem_pallas

    rng = np.random.default_rng(1)
    seeds = jnp.asarray(rng.integers(0, 256, (B, 34), dtype=np.uint8))
    ref = np.asarray(mlkem.sample_ntt(seeds))
    ph, plo, batch = keccak.seed_block_words(seeds, 168, 0x1F)
    got = np.asarray(mlkem_pallas.sample_ntt_words(ph, plo).T.reshape(batch + (256,)))
    assert np.array_equal(got, ref), "sample_ntt_words diverges from jnp path"


def check_cbd(eta: int) -> None:
    import jax.numpy as jnp

    from quantum_resistant_p2p_tpu.core import keccak
    from quantum_resistant_p2p_tpu.kem import mlkem, mlkem_pallas

    rng = np.random.default_rng(2 + eta)
    s = jnp.asarray(rng.integers(0, 256, (B, 32), dtype=np.uint8))
    n_consts = np.arange(2, dtype=np.uint8)
    ref = np.asarray(mlkem._prf_cbd(s, n_consts, eta))
    seeds = mlkem._prf_seeds(s, n_consts)
    ph, plo, _ = keccak.seed_block_words(seeds.reshape(-1, 33), 136, 0x1F)
    got = np.asarray(
        mlkem_pallas.cbd_words(ph, plo, eta=eta).T.reshape(B, 2, 256)
    )
    assert np.array_equal(got, ref), f"cbd_words(eta={eta}) diverges from jnp path"


def check_rej_ntt() -> None:
    import jax.numpy as jnp

    from quantum_resistant_p2p_tpu.core import keccak
    from quantum_resistant_p2p_tpu.sig import mldsa, mldsa_pallas

    rng = np.random.default_rng(4)
    seeds = jnp.asarray(rng.integers(0, 256, (B, 34), dtype=np.uint8))
    ref = np.asarray(mldsa.rej_ntt_poly(seeds))
    ph, plo, batch = keccak.seed_block_words(seeds, 168, 0x1F)
    got = np.asarray(mldsa_pallas.rej_ntt_words(ph, plo).T.reshape(batch + (256,)))
    assert np.array_equal(got, ref), "rej_ntt_words diverges from jnp path"


def check_rej_bounded(eta: int) -> None:
    import jax.numpy as jnp

    from quantum_resistant_p2p_tpu.core import keccak
    from quantum_resistant_p2p_tpu.sig import mldsa, mldsa_pallas

    rng = np.random.default_rng(6 + eta)
    seeds = jnp.asarray(rng.integers(0, 256, (B, 66), dtype=np.uint8))
    ref = np.asarray(mldsa.rej_bounded_poly(eta, seeds))
    ph, plo, batch = keccak.seed_block_words(seeds, 136, 0x1F)
    z = mldsa_pallas.rej_bounded_words(ph, plo, eta=eta).T.reshape(batch + (256,))
    # production applies the eta-map AFTER the kernel (sig/mldsa.py)
    got = np.asarray((2 - z % 5) % mldsa.Q if eta == 2 else (4 - z) % mldsa.Q)
    assert np.array_equal(got, ref), f"rej_bounded_words(eta={eta}) diverges"


def check_sha256_compress() -> None:
    import jax.numpy as jnp

    from quantum_resistant_p2p_tpu.core import sha256, sha256_pallas

    rng = np.random.default_rng(9)
    state = jnp.asarray(rng.integers(0, 1 << 32, (B, 8), dtype=np.uint32))
    block = jnp.asarray(rng.integers(0, 256, (B, 64), dtype=np.uint8))
    ref = np.asarray(sha256.compress(state, block))
    sw = state.reshape(B, 8).T
    bw = sha256._block_words(block).reshape(B, 16).T
    got = np.asarray(sha256_pallas.compress_words(sw, bw).T.reshape(B, 8))
    assert np.array_equal(got, ref), "sha256 compress_words diverges from jnp path"


def check_sha512_compress() -> None:
    import jax.numpy as jnp

    from quantum_resistant_p2p_tpu.core import sha512, sha512_pallas

    rng = np.random.default_rng(10)
    sh = jnp.asarray(rng.integers(0, 1 << 32, (B, 8), dtype=np.uint32))
    sl = jnp.asarray(rng.integers(0, 1 << 32, (B, 8), dtype=np.uint32))
    block = jnp.asarray(rng.integers(0, 256, (B, 128), dtype=np.uint8))
    rh, rl = sha512.compress((sh, sl), block)
    bh, bl = sha512._block_words(block)
    oh, ol = sha512_pallas.compress_words(sh.T, sl.T, bh.T, bl.T)
    assert np.array_equal(np.asarray(oh.T), np.asarray(rh)), "sha512 hi diverges"
    assert np.array_equal(np.asarray(ol.T), np.asarray(rl)), "sha512 lo diverges"


def check_hqc_fft_cyclic() -> None:
    """On-chip bit-exactness of the f32-FFT cyclic product (the HQC
    default) vs the exact Toeplitz-MXU formulation, at every parameter
    set, on the precision-worst-case input (dense = all ones — maximal
    spectral norm).  The CPU suite asserts the same thing, but TPU FFT
    accuracy differs from CPU FFT accuracy, and the KEM-level FO
    roundtrip cannot catch a deterministic flip (encaps and decaps would
    reproduce it identically) — this is the direct device check."""
    import jax.numpy as jnp

    from quantum_resistant_p2p_tpu.kem import hqc
    from quantum_resistant_p2p_tpu.pyref.hqc_ref import PARAMS

    rng = np.random.default_rng(14)
    for name in ("HQC-128", "HQC-192", "HQC-256"):
        p = PARAMS[name]
        dense = jnp.asarray(np.stack([
            np.ones(p.n, np.int32),
            rng.integers(0, 2, p.n, dtype=np.int32),
        ]))
        sup = jnp.asarray(np.stack([
            rng.choice(p.n, size=p.w, replace=False).astype(np.int32),
            rng.choice(p.n, size=p.w, replace=False).astype(np.int32),
        ]))
        got = np.asarray(hqc._cyclic_mul_fft(p, dense, sup))
        ref = np.asarray(hqc._cyclic_mul_matmul(p, dense, sup))
        assert np.array_equal(got, ref), f"FFT cyclic product diverges on-chip: {name}"


def check_sponge() -> None:
    """shake256 through sponge_words (multi-block absorb+squeeze) vs jnp."""
    import jax.numpy as jnp

    from quantum_resistant_p2p_tpu.core import keccak, keccak_pallas

    rng = np.random.default_rng(12)
    msgs = jnp.asarray(rng.integers(0, 256, (B, 64), dtype=np.uint8))
    ref = np.asarray(keccak.shake256(msgs, 272))  # 2 squeeze blocks
    block = keccak.pad_single_block(msgs, 136, 0x1F)
    ph, plo = keccak._bytes_to_words(block)
    oh, ol = keccak_pallas.sponge_words(
        ph.T, plo.T, rate_words=17, n_abs=1, n_sq=2
    )
    got = np.asarray(keccak._words_to_bytes(oh.T, ol.T))[:, :272]
    assert np.array_equal(got, ref), "sponge_words diverges from jnp path"


def check_mldsa_ntt() -> None:
    """On-chip (inv)NTT kernel vs the jnp stage-loop transforms, including
    the non-tile-aligned lane padding path and a round-trip."""
    import jax.numpy as jnp

    from quantum_resistant_p2p_tpu.sig import mldsa, mldsa_pallas

    rng = np.random.default_rng(23)
    for lanes in (B, 130):  # tile-aligned and padded
        f = rng.integers(0, mldsa.Q, (lanes, 256), dtype=np.int32)
        # reference = the jnp stage loop, independent of QRP2P_PALLAS routing
        zref = _mldsa_ntt_jnp(f)
        got = np.asarray(mldsa_pallas.ntt_words(jnp.asarray(f.T))).T
        assert np.array_equal(got, zref), f"ntt_words diverges (lanes={lanes})"
        gi = np.asarray(
            mldsa_pallas.ntt_words(jnp.asarray(got.T), inverse=True)
        ).T
        assert np.array_equal(gi, f), f"ntt_inv round-trip fails (lanes={lanes})"


def _mldsa_ntt_jnp(f: np.ndarray) -> np.ndarray:
    """The jnp stage-loop NTT, inlined so the check is independent of the
    QRP2P_PALLAS routing inside mldsa.ntt."""
    import jax.numpy as jnp

    from quantum_resistant_p2p_tpu.sig.mldsa import _ZETAS, _mm, N, Q

    zetas = jnp.asarray(_ZETAS)
    g = jnp.asarray(f)
    k = 1
    length = 128
    while length >= 1:
        groups = N // (2 * length)
        z = zetas[k : k + groups]
        fr = g.reshape(g.shape[:-1] + (groups, 2, length))
        f0, f1 = fr[..., 0, :], fr[..., 1, :]
        t = _mm(jnp.broadcast_to(z[:, None], f1.shape), f1)
        g = jnp.stack([(f0 + t) % Q, (f0 - t) % Q], axis=-2).reshape(g.shape)
        k += groups
        length //= 2
    return np.asarray(g)


CHECKS = [
    ("sample_ntt_words", check_sample_ntt),
    ("cbd_words eta=2", lambda: check_cbd(2)),
    ("cbd_words eta=3", lambda: check_cbd(3)),
    ("rej_ntt_words", check_rej_ntt),
    ("rej_bounded_words eta=2", lambda: check_rej_bounded(2)),
    ("rej_bounded_words eta=4", lambda: check_rej_bounded(4)),
    ("sha256 compress_words", check_sha256_compress),
    ("sha512 compress_words", check_sha512_compress),
    ("sponge_words shake256", check_sponge),
    ("hqc fft cyclic product", check_hqc_fft_cyclic),
    ("mldsa ntt/ntt_inv words", check_mldsa_ntt),
]


def main() -> int:
    import jax

    platform = jax.devices()[0].platform
    print(f"platform: {platform}")
    if platform != "tpu":
        print("WARNING: not a TPU — Mosaic is the point of this check")
    failed = 0
    for name, fn in CHECKS:
        try:
            fn()
            print(f"  ok   {name}")
        except AssertionError as e:
            failed += 1
            print(f"  FAIL {name}: {e}")
    print(f"{len(CHECKS) - failed}/{len(CHECKS)} pallas_call launchers bit-exact")
    return 1 if failed else 0


if __name__ == "__main__":
    raise SystemExit(main())
