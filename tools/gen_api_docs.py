"""Generate the API reference (docs/api/*.md) from live docstrings.

The reference ships an mkdocs + mkdocstrings setup (reference mkdocs.yml
+ docs/api/** stubs); this is the equivalent for an offline environment:
one markdown page per package section — module docstring, then every
public class (with method signatures + first docstring paragraph) and
function — generated from the imported modules so it can never drift
silently from the code.  Re-run after API changes:

    JAX_PLATFORMS=cpu python -m tools.gen_api_docs
"""

from __future__ import annotations

import importlib
import inspect
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

OUT_DIR = Path(__file__).resolve().parent.parent / "docs" / "api"

SECTIONS: dict[str, list[str]] = {
    "provider": [
        "quantum_resistant_p2p_tpu.provider.base",
        "quantum_resistant_p2p_tpu.provider.registry",
        "quantum_resistant_p2p_tpu.provider.kem_providers",
        "quantum_resistant_p2p_tpu.provider.sig_providers",
        "quantum_resistant_p2p_tpu.provider.symmetric",
        "quantum_resistant_p2p_tpu.provider.aead_device",
        "quantum_resistant_p2p_tpu.provider.batched",
        "quantum_resistant_p2p_tpu.provider.scheduler",
        "quantum_resistant_p2p_tpu.provider.autotune",
        "quantum_resistant_p2p_tpu.provider.opcache",
        "quantum_resistant_p2p_tpu.provider.health",
        "quantum_resistant_p2p_tpu.faults.plan",
    ],
    "kem": [
        "quantum_resistant_p2p_tpu.kem.mlkem",
        "quantum_resistant_p2p_tpu.kem.frodo",
        "quantum_resistant_p2p_tpu.kem.hqc",
    ],
    "sig": [
        "quantum_resistant_p2p_tpu.sig.mldsa",
        "quantum_resistant_p2p_tpu.sig.sphincs",
    ],
    "core": [
        "quantum_resistant_p2p_tpu.core.keccak",
        "quantum_resistant_p2p_tpu.core.chacha_pallas",
        "quantum_resistant_p2p_tpu.core.sha256",
        "quantum_resistant_p2p_tpu.core.sha512",
        "quantum_resistant_p2p_tpu.core.aes",
        "quantum_resistant_p2p_tpu.core.aes_bitsliced",
        "quantum_resistant_p2p_tpu.core.sortnet",
        "quantum_resistant_p2p_tpu.pyref.chacha_ref",
    ],
    "app-net-storage": [
        "quantum_resistant_p2p_tpu.app.messaging",
        "quantum_resistant_p2p_tpu.app.resumption",
        "quantum_resistant_p2p_tpu.app.message_store",
        "quantum_resistant_p2p_tpu.net.p2p_node",
        "quantum_resistant_p2p_tpu.net.discovery",
        "quantum_resistant_p2p_tpu.net.identity",
        "quantum_resistant_p2p_tpu.storage.key_storage",
        "quantum_resistant_p2p_tpu.storage.secure_logger",
        "quantum_resistant_p2p_tpu.storage.secure_file",
    ],
    "runtime": [
        "quantum_resistant_p2p_tpu.cli",
        "quantum_resistant_p2p_tpu.tui",
        "quantum_resistant_p2p_tpu.config",
        "quantum_resistant_p2p_tpu.parallel.mesh",
        "quantum_resistant_p2p_tpu.utils.benchmarking",
        "quantum_resistant_p2p_tpu.utils.ctr_drbg",
    ],
    "obs": [
        "quantum_resistant_p2p_tpu.obs.trace",
        "quantum_resistant_p2p_tpu.obs.metrics",
        "quantum_resistant_p2p_tpu.obs.slo",
        "quantum_resistant_p2p_tpu.obs.cost",
        "quantum_resistant_p2p_tpu.obs.http",
        "quantum_resistant_p2p_tpu.obs.flight",
    ],
    "analysis": [
        "tools.analysis.engine",
        "tools.analysis.flow",
        "tools.analysis.flow.callgraph",
        "tools.analysis.flow.taint",
        "tools.analysis.flow.domains",
        "tools.analysis.flow.packs",
        "tools.analysis.flow.sarif",
        "tools.analysis.kernel",
        "tools.analysis.kernel.absdom",
        "tools.analysis.kernel.interp",
        "tools.analysis.kernel.models",
        "tools.analysis.kernel.shapes",
        "tools.analysis.kernel.pallas_checks",
        "tools.analysis.kernel.dataflow",
        "tools.analysis.kernel.packs",
        "tools.analysis.proto",
        "tools.analysis.proto.model",
        "tools.analysis.proto.packs",
        "tools.analysis.life",
        "tools.analysis.life.locks",
        "tools.analysis.life.resources",
        "tools.analysis.life.wipes",
        "tools.analysis.life.packs",
        "tools.analysis.all",
    ],
}


def _first_para(doc: str | None) -> str:
    if not doc:
        return ""
    return inspect.cleandoc(doc).split("\n\n")[0]


def _sig(obj) -> str:
    try:
        return str(inspect.signature(obj))
    except (ValueError, TypeError):
        return "(...)"


def _public_members(mod):
    for name, obj in sorted(vars(mod).items()):
        if name.startswith("_"):
            continue
        if getattr(obj, "__module__", None) != mod.__name__:
            continue  # re-exports documented at their home
        yield name, obj


def render_module(modname: str) -> str:
    mod = importlib.import_module(modname)
    lines = [f"## `{modname}`", ""]
    doc = inspect.cleandoc(mod.__doc__ or "").strip()
    if doc:
        lines += [doc, ""]
    for name, obj in _public_members(mod):
        if inspect.isclass(obj):
            lines += [f"### class `{name}{_sig(obj)}`", ""]
            para = _first_para(obj.__doc__)
            if para:
                lines += [para, ""]
            for mname, meth in sorted(vars(obj).items()):
                if mname.startswith("_") or not callable(meth):
                    continue
                lines.append(f"- `{mname}{_sig(meth)}` — {_first_para(meth.__doc__) or ''}")
            lines.append("")
        elif inspect.isfunction(obj):
            lines += [f"### `{name}{_sig(obj)}`", ""]
            para = _first_para(obj.__doc__)
            if para:
                lines += [para, ""]
    return "\n".join(lines)


def main(argv: list[str] | None = None) -> int:
    import argparse

    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--only", action="append", metavar="SECTION",
                    help="regenerate only these section page(s); other pages "
                         "are left untouched (useful on minimal images where "
                         "some sections' modules cannot import)")
    args = ap.parse_args(argv)
    wanted = set(args.only or SECTIONS)
    unknown = wanted - set(SECTIONS)
    if unknown:
        print(f"unknown section(s): {', '.join(sorted(unknown))}", file=sys.stderr)
        return 2
    OUT_DIR.mkdir(parents=True, exist_ok=True)
    index = ["# API reference", "",
             "Generated from docstrings by `tools/gen_api_docs.py`; regenerate "
             "after API changes.", ""]
    for section, modules in SECTIONS.items():
        if section in wanted:
            page = [f"# {section}", ""]
            for modname in modules:
                page.append(render_module(modname))
                page.append("")
            out = OUT_DIR / f"{section}.md"
            out.write_text("\n".join(page))
            print(f"wrote {out}")
        index.append(f"- [{section}]({section}.md): " + ", ".join(
            f"`{m.split('.')[-1]}`" for m in modules))
    (OUT_DIR / "README.md").write_text("\n".join(index) + "\n")
    print(f"wrote {OUT_DIR / 'README.md'}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
