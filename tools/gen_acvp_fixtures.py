"""Generate official-format KAT fixtures for every PQC family.

Writes, into ``tests/vectors/``:

  acvp_mldsa44_fixture.json        ACVP-shaped keyGen/sigGen/sigVer (internal)
  acvp_slhdsa128f_fixture.json     ACVP-shaped keyGen/sigGen/sigVer (internal)
  PQCgenKAT_mlkem512_fixture.rsp   PQCgenKAT stanzas (DRBG stream d||z, m)
  PQCgenKAT_frodo640shake_fixture.rsp  (DRBG stream s||seedSE||z16, mu)
  PQCgenKAT_hqc128_fixture.rsp     (THIS framework's seam; see correctness.md)

These keep tools/verify_vectors.py's official-format parsing + DRBG seam
paths green for all five families until real NIST/ACVP files can be dropped
in (this environment has no egress).  Every file is marked as a qrp2p
fixture so the verifier reports provenance honestly.

Usage: python -m tools.gen_acvp_fixtures
"""

from __future__ import annotations

import json
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

from quantum_resistant_p2p_tpu.pyref import (  # noqa: E402
    frodo_ref,
    hqc_ref,
    mldsa_ref,
    mlkem_ref,
    slhdsa_ref,
)
from quantum_resistant_p2p_tpu.utils.ctr_drbg import CtrDrbg  # noqa: E402

VECTOR_DIR = Path(__file__).resolve().parent.parent / "tests" / "vectors"
N_TESTS = 3


def _drbg(label: bytes) -> CtrDrbg:
    return CtrDrbg(label.ljust(48, b"\0")[:48])


def gen_acvp_mldsa() -> dict:
    p = mldsa_ref.PARAMS["ML-DSA-44"]
    rng = _drbg(b"qrp2p acvp mldsa fixture")
    keygen_tests, siggen_tests, sigver_tests = [], [], []
    for i in range(N_TESTS):
        seed = rng.random_bytes(32)
        pk, sk = mldsa_ref.keygen(p, seed)
        keygen_tests.append(
            {"tcId": i + 1, "seed": seed.hex(), "pk": pk.hex(), "sk": sk.hex()}
        )
        message = rng.random_bytes(33 + i)  # internal interface: raw M'
        rnd = rng.random_bytes(32)
        sig = mldsa_ref.sign_internal(p, sk, message, rnd)
        siggen_tests.append(
            {"tcId": i + 1, "sk": sk.hex(), "message": message.hex(),
             "rnd": rnd.hex(), "signature": sig.hex()}
        )
        tampered = i == N_TESTS - 1
        sigver_tests.append(
            {"tcId": i + 1, "pk": pk.hex(),
             "message": (message[:-1] + bytes([message[-1] ^ 1])).hex()
             if tampered else message.hex(),
             "signature": sig.hex(), "testPassed": not tampered}
        )
    return {
        "vsId": 0,
        "algorithm": "ML-DSA-44",
        "mode": "internal",
        "source": "qrp2p-generated-fixture (not an official ACVP file)",
        "testGroups": [
            {"tgId": 1, "testType": "AFT", "tests": keygen_tests},
            {"tgId": 2, "testType": "AFT", "tests": siggen_tests},
            {"tgId": 3, "testType": "AFT", "tests": sigver_tests},
        ],
    }


def gen_acvp_slhdsa() -> dict:
    p = slhdsa_ref.PARAMS["SPHINCS+-SHA2-128f-simple"]
    rng = _drbg(b"qrp2p acvp slhdsa fixture")
    keygen_tests, siggen_tests, sigver_tests = [], [], []
    for i in range(2):  # SPHINCS+ signing is slow in pure Python
        ss, sp, ps = (rng.random_bytes(p.n) for _ in range(3))
        pk, sk = slhdsa_ref.keygen(p, ss, sp, ps)
        keygen_tests.append(
            {"tcId": i + 1, "skSeed": ss.hex(), "skPrf": sp.hex(),
             "pkSeed": ps.hex(), "pk": pk.hex(), "sk": sk.hex()}
        )
        message = rng.random_bytes(24 + i)
        sig = slhdsa_ref.sign_internal(p, message, sk, None)  # deterministic
        siggen_tests.append(
            {"tcId": i + 1, "sk": sk.hex(), "message": message.hex(),
             "signature": sig.hex()}
        )
        tampered = i == 1
        sigver_tests.append(
            {"tcId": i + 1, "pk": pk.hex(),
             "message": (message[:-1] + bytes([message[-1] ^ 1])).hex()
             if tampered else message.hex(),
             "signature": sig.hex(), "testPassed": not tampered}
        )
    return {
        "vsId": 0,
        "algorithm": "SPHINCS+-SHA2-128f-simple",
        "mode": "internal",
        "source": "qrp2p-generated-fixture (not an official ACVP file)",
        "testGroups": [
            {"tgId": 1, "testType": "AFT", "tests": keygen_tests},
            {"tgId": 2, "testType": "AFT", "tests": siggen_tests},
            {"tgId": 3, "testType": "AFT", "tests": sigver_tests},
        ],
    }


def _rsp_header(note: str) -> list[str]:
    return [f"# qrp2p generated fixture — {note}", ""]


def gen_rsp_mlkem() -> str:
    p = mlkem_ref.PARAMS["ML-KEM-512"]
    master = _drbg(b"qrp2p rsp mlkem fixture")
    lines = _rsp_header("PQCgenKAT shape, DRBG stream d||z then m")
    for i in range(N_TESTS):
        seed = master.random_bytes(48)
        drbg = CtrDrbg(seed)
        d, z = drbg.random_bytes(32), drbg.random_bytes(32)
        ek, dk = mlkem_ref.keygen(p, d, z)
        m = drbg.random_bytes(32)
        k, c = mlkem_ref.encaps(p, ek, m)
        lines += [f"count = {i}", f"seed = {seed.hex().upper()}",
                  f"pk = {ek.hex().upper()}", f"sk = {dk.hex().upper()}",
                  f"ct = {c.hex().upper()}", f"ss = {k.hex().upper()}", ""]
    return "\n".join(lines)


def gen_rsp_frodo() -> str:
    p = frodo_ref.PARAMS["FrodoKEM-640-SHAKE"]
    master = _drbg(b"qrp2p rsp frodo fixture")
    lines = _rsp_header("PQCgenKAT shape, DRBG stream s||seedSE||z(16) then mu")
    for i in range(N_TESTS):
        seed = master.random_bytes(48)
        drbg = CtrDrbg(seed)
        r = drbg.random_bytes(2 * p.len_sec + 16)
        pk, sk = frodo_ref.keygen(
            p, r[: p.len_sec], r[p.len_sec : 2 * p.len_sec], r[2 * p.len_sec :]
        )
        mu = drbg.random_bytes(p.len_sec)
        ct, ss = frodo_ref.encaps(p, pk, mu)
        lines += [f"count = {i}", f"seed = {seed.hex().upper()}",
                  f"pk = {pk.hex().upper()}", f"sk = {sk.hex().upper()}",
                  f"ct = {ct.hex().upper()}", f"ss = {ss.hex().upper()}", ""]
    return "\n".join(lines)


def gen_rsp_hqc() -> str:
    p = hqc_ref.PARAMS["HQC-128"]
    master = _drbg(b"qrp2p rsp hqc fixture")
    lines = _rsp_header(
        "qrp2p seam: DRBG stream sk_seed(40)||sigma(k)||pk_seed(40), m||salt "
        "— reconstructed official round-4 randombytes order, unverified "
        "offline (docs/correctness.md §HQC seam)"
    )
    for i in range(N_TESTS):
        seed = master.random_bytes(48)
        drbg = CtrDrbg(seed)
        sk_seed, sigma, pk_seed = (
            drbg.random_bytes(40), drbg.random_bytes(p.k), drbg.random_bytes(40)
        )
        pk, sk = hqc_ref.keygen(p, sk_seed, sigma, pk_seed)
        m, salt = drbg.random_bytes(p.k), drbg.random_bytes(16)
        ct, ss = hqc_ref.encaps(p, pk, m, salt)
        lines += [f"count = {i}", f"seed = {seed.hex().upper()}",
                  f"pk = {pk.hex().upper()}", f"sk = {sk.hex().upper()}",
                  f"ct = {ct.hex().upper()}", f"ss = {ss.hex().upper()}", ""]
    return "\n".join(lines)


def main() -> int:
    outputs = {
        "acvp_mldsa44_fixture.json": json.dumps(gen_acvp_mldsa(), indent=1),
        "acvp_slhdsa128f_fixture.json": json.dumps(gen_acvp_slhdsa(), indent=1),
        "PQCgenKAT_mlkem512_fixture.rsp": gen_rsp_mlkem(),
        "PQCgenKAT_frodo640shake_fixture.rsp": gen_rsp_frodo(),
        "PQCgenKAT_hqc128_fixture.rsp": gen_rsp_hqc(),
    }
    for name, content in outputs.items():
        (VECTOR_DIR / name).write_text(content)
        print(f"wrote {name} ({len(content)} bytes)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
