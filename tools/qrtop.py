"""qrtop — a terminal dashboard over the fleet's live telemetry endpoints.

Polls N per-gateway telemetry surfaces (obs/http.py: ``/healthz``,
``/readyz``, ``/cost``, ``/slo``, ``/metrics.json``) and renders one row
per gateway: handshakes/s, shed rate, SLO burn, breaker/shard states,
padding-waste fraction, and live compile activity — the serving-cost
economics (docs/observability.md "Reading the cost ledger") as a
top(1)-style loop instead of a post-hoc artifact.

Endpoints come from the command line (``host:port`` or
``name=host:port``) or are discovered from a fleet router's aggregated
``/fleet`` view (``--fleet host:port`` — fleet/manager.py announces each
gateway's telemetry port from its hello/heartbeats).  With a REPLICATED
control plane (fleet/router.py), pass ``--fleet`` once per router:
discovery falls back across the replicas (any one reachable is enough),
and each router renders as its own row with a ROLE column
(leader/follower/demoted — the live lease view, docs/fleet.md "HA
control plane").

``--snapshot`` takes ONE poll and emits the JSON document instead of
rendering — the CI artifact mode (``bench.py --storm --fleet N`` runs
this exact function against the live mid-storm gateways to produce the
committed ``bench_results/fleet_storm_cost_snapshot.json``).

Stdlib-only (urllib + json): runs wherever the gateways do.

Usage::

    python tools/qrtop.py 127.0.0.1:9100 gw1=127.0.0.1:9101
    python tools/qrtop.py --fleet 127.0.0.1:9000 --interval 2
    python tools/qrtop.py --fleet 127.0.0.1:9000 --snapshot --out snap.json
    python tools/qrtop.py --fleet 127.0.0.1:9000 --fleet 127.0.0.1:9001
"""

from __future__ import annotations

import argparse
import json
import sys
import time
import urllib.error
import urllib.request
from typing import Any

#: per-request scrape timeout: a dead gateway must cost one bounded wait,
#: never hang the dashboard loop
SCRAPE_TIMEOUT_S = 3.0


def fetch_json(base: str, path: str,
               timeout: float = SCRAPE_TIMEOUT_S) -> dict[str, Any] | None:
    """GET ``http://{base}{path}`` as JSON; None when unreachable or
    malformed (a dead gateway is a row that says so, not a crash).
    Non-200 readiness replies still carry a JSON body — parse them."""
    try:
        with urllib.request.urlopen(f"http://{base}{path}",
                                    timeout=timeout) as resp:
            return json.loads(resp.read())
    except urllib.error.HTTPError as e:
        try:
            return json.loads(e.read())
        except (ValueError, OSError):
            return None
    except (OSError, ValueError):
        return None


def _sum_compiles(cost: dict[str, Any]) -> tuple[int, float]:
    events = seconds = 0
    for row in (cost.get("compiles") or {}).values():
        events += row.get("events") or 0
        seconds += row.get("seconds") or 0.0
    return events, round(seconds, 3)


def _opcache_rates(cost: dict[str, Any]) -> dict[str, Any]:
    return {kind: c.get("window_hit_rate")
            for kind, c in (cost.get("opcaches") or {}).items()}


def scrape_gateway(name: str, base: str) -> dict[str, Any]:
    """One gateway's dashboard row, from its live endpoints."""
    health = fetch_json(base, "/healthz")
    if health is None:
        return {"gateway": name, "endpoint": base, "reachable": False}
    ready = fetch_json(base, "/readyz") or {}
    cost = fetch_json(base, "/cost") or {}
    slo = fetch_json(base, "/slo") or {}
    snap = fetch_json(base, "/metrics.json") or {}
    counters = snap.get("counters") or {}
    queues = (snap.get("collected") or {}).get("queues") or {}
    uptime = float(health.get("uptime_s") or 0.0)
    # both halves of the handshake work: a pure gateway only RESPONDS,
    # so its rate lives in the admitted count, not the initiator one
    handshakes = (int(health.get("handshake_attempts") or 0)
                  + int(health.get("handshakes_admitted") or 0))
    compile_events, compile_seconds = _sum_compiles(cost)
    burns = {s.get("name"): s.get("burn_fast")
             for s in (slo.get("specs") or [])}
    return {
        "gateway": name,
        "endpoint": base,
        "reachable": True,
        "node": health.get("node"),
        "uptime_s": round(uptime, 3),
        "ready": bool(ready.get("ready")),
        # a draining gateway (503 /readyz with the reason) renders as the
        # DRAIN state: a rolling restart is visible live, gateway by
        # gateway, instead of reading as mystery unreadiness
        "draining": bool(ready.get("draining")),
        "drain_reason": ready.get("drain_reason"),
        "breakers": ready.get("breakers") or {},
        "handshakes": handshakes,
        "handshake_attempts": int(health.get("handshake_attempts") or 0),
        "hs_per_s": round(handshakes / uptime, 3) if uptime > 0 else None,
        "handshake_sheds": counters.get("handshake_sheds"),
        "handshakes_admitted": counters.get("handshakes_admitted"),
        "bulk_sheds": counters.get("bulk_sheds"),
        "device_served_fraction": queues.get("device_served_fraction"),
        "breaker_state": queues.get("breaker_state"),
        "padding_waste_fraction": cost.get("padding_waste_fraction"),
        "device_seconds_total": cost.get("device_seconds_total"),
        "device_seconds_per_1k_handshakes":
            cost.get("device_seconds_per_1k_handshakes"),
        "compile_events": compile_events,
        "compile_seconds": compile_seconds,
        "recent_compiles": (cost.get("recent_compiles") or [])[-3:],
        "opcache_window_hit_rate": _opcache_rates(cost),
        "tuner_journal_len": cost.get("tuner_journal_len"),
        "slo_alerting": slo.get("alerting") or [],
        "burn_fast": burns,
    }


def scrape_router(name: str, base: str) -> dict[str, Any]:
    """One ROUTER replica's dashboard row, from its ``/fleet`` view: the
    live lease role (leader/follower/demoted), epoch/holder, and the
    control-plane counters — a demoted or dead replica is a visible row,
    the whole point of watching a failover live."""
    doc = fetch_json(base, "/fleet")
    router = (doc or {}).get("router") or {}
    if not router:
        return {"router": name, "endpoint": base, "reachable": False}
    lease = router.get("lease") or {}
    return {
        "router": str(router.get("router_id") or name),
        "endpoint": base,
        "reachable": True,
        "role": lease.get("role"),
        "epoch": lease.get("epoch"),
        "holder": lease.get("holder"),
        "standalone": bool(lease.get("standalone")),
        "gateways": router.get("gateways"),
        "routes_ok": router.get("routes_ok"),
        "route_sheds": router.get("route_sheds"),
        "stek_rotations": router.get("stek_rotations"),
        "lease_rejects": router.get("lease_rejects"),
        "lease_fenced": router.get("lease_fenced"),
        "syncs_applied": router.get("syncs_applied"),
    }


def snapshot_endpoints(endpoints: dict[str, str],
                       routers: dict[str, str] | None = None
                       ) -> dict[str, Any]:
    """One-shot scrape of every endpoint — the ``--snapshot`` document
    (also called in-harness by ``fleet/storm.py`` while the gateways are
    live, which is how the committed CI artifact is produced)."""
    doc: dict[str, Any] = {
        "tool": "qrtop --snapshot",
        "endpoints": dict(endpoints),
        "gateways": {name: scrape_gateway(name, base)
                     for name, base in sorted(endpoints.items())},
    }
    if routers:
        doc["routers"] = {name: scrape_router(name, base)
                          for name, base in sorted(routers.items())}
    return doc


def discover_fleet(routers: list[str]) -> tuple[dict[str, str],
                                                dict[str, str]]:
    """Gateway + router telemetry endpoints from the replicas' ``/fleet``
    views, falling back across ``routers`` — with a replicated control
    plane any ONE reachable replica can describe the whole fleet, so a
    dead leader must not blind the dashboard.  Returns
    ``(gateway_endpoints, router_endpoints)``; raises only when every
    replica is unreachable."""
    gw_eps: dict[str, str] = {}
    rt_eps: dict[str, str] = {}
    any_reachable = False
    for i, router in enumerate(routers):
        doc = fetch_json(router, "/fleet")
        host = router.rsplit(":", 1)[0]
        if doc is None:
            rt_eps.setdefault(f"rt?{i}", router)
            continue
        any_reachable = True
        rview = doc.get("router") or {}
        rt_eps[str(rview.get("router_id") or f"rt{i}")] = router
        for member in (rview.get("members") or []):
            port = member.get("telemetry_port")
            if port:
                # first reachable replica wins per gateway (they all
                # describe the same announced ports)
                gw_eps.setdefault(str(member.get("gateway")),
                                  f"{host}:{port}")
    if not any_reachable:
        raise SystemExit("qrtop: no /fleet view at any of "
                         + ", ".join(f"http://{r}" for r in routers))
    return gw_eps, rt_eps


# -- live rendering ------------------------------------------------------------


def _fmt(v: Any, pct: bool = False) -> str:
    if v is None:
        return "-"
    if pct:
        return f"{v * 100:.1f}%"
    if isinstance(v, float):
        return f"{v:.2f}"
    return str(v)


def render_routers(rows: list[dict[str, Any]]) -> str:
    """The control-plane header block: one line per router replica with
    its live lease ROLE — a failover reads as the leader row going
    unreachable and a follower row flipping to leader; a split-brain
    averted reads as a demoted row."""
    cols = ("ROUTER", "ROLE", "EPOCH", "HOLDER", "GWS", "ROUTES", "SHED",
            "SYNCS", "FENCED")
    lines = ["  ".join(f"{c:<10}" for c in cols)]
    for row in rows:
        name = row["router"]
        if not row.get("reachable"):
            lines.append(f"{name:<10}  [unreachable: {row['endpoint']}]")
            continue
        role = ("standalone" if row.get("standalone")
                else row.get("role") or "-")
        vals = (name, role, _fmt(row.get("epoch")),
                row.get("holder") or "-", _fmt(row.get("gateways")),
                _fmt(row.get("routes_ok")), _fmt(row.get("route_sheds")),
                _fmt(row.get("syncs_applied")), _fmt(row.get("lease_fenced")))
        lines.append("  ".join(f"{v:<10}" for v in vals))
    return "\n".join(lines)


def render(rows: list[dict[str, Any]], prev: dict[str, dict[str, Any]],
           elapsed: float) -> str:
    """One dashboard frame.  hs/s comes from the poll-to-poll delta over
    the REAL elapsed seconds when a previous sample exists (the live
    rate), else the uptime average."""
    cols = ("GATEWAY", "UP(s)", "STATE", "RDY", "HS", "HS/S", "SHED",
            "WASTE", "COMP(n/s)", "OPCACHE", "BURN", "BREAKERS")
    lines = ["  ".join(f"{c:<10}" for c in cols)]
    for row in rows:
        name = row["gateway"]
        if not row.get("reachable"):
            lines.append(f"{name:<10}  [unreachable: {row['endpoint']}]")
            continue
        last = prev.get(name)
        if last and elapsed > 0:
            hs_rate = (row["handshakes"]
                       - last.get("handshakes", 0)) / elapsed
        else:
            hs_rate = row.get("hs_per_s")
        sheds = sum(row.get(k) or 0 for k in
                    ("handshake_sheds", "bulk_sheds"))
        comp = f"{row['compile_events']}/{row['compile_seconds']:.1f}"
        opc = ",".join(f"{k}:{_fmt(v, pct=True)}" for k, v in
                       sorted(row["opcache_window_hit_rate"].items())) or "-"
        burn = max((b for b in row["burn_fast"].values()
                    if isinstance(b, (int, float))), default=None)
        breakers = ",".join(f"{k}:{v}" for k, v in
                            sorted(row["breakers"].items())) or "-"
        alert = "!" if row["slo_alerting"] else ""
        # DRAIN makes a rolling restart legible live; otherwise the
        # state is simply whether the gateway serves (run) or not
        state = "DRAIN" if row.get("draining") else "run"
        vals = (name, _fmt(row["uptime_s"]), state,
                "y" if row["ready"] else "N",
                str(row["handshakes"]), _fmt(hs_rate), str(sheds),
                _fmt(row["padding_waste_fraction"], pct=True), comp, opc,
                _fmt(burn) + alert, breakers)
        lines.append("  ".join(f"{v:<10}" for v in vals))
    return "\n".join(lines)


def live_loop(endpoints: dict[str, str], interval: float,
              iterations: int | None = None, out=sys.stdout,
              routers: dict[str, str] | None = None) -> None:
    prev: dict[str, dict[str, Any]] = {}
    prev_t: float | None = None
    n = 0
    while iterations is None or n < iterations:
        router_rows = [scrape_router(name, base)
                       for name, base in sorted((routers or {}).items())]
        rows = [scrape_gateway(name, base)
                for name, base in sorted(endpoints.items())]
        # rates divide by the REAL elapsed time since the last frame, not
        # the nominal interval — the serial scrape itself takes time (up
        # to timeout x endpoints when a gateway is black-holed), and
        # dividing by the nominal interval would inflate HS/S by exactly
        # that slippage
        now = time.monotonic()
        elapsed = (now - prev_t) if prev_t is not None else 0.0
        prev_t = now
        frame = render(rows, prev, elapsed)
        if router_rows:
            frame = render_routers(router_rows) + "\n\n" + frame
        # ANSI home+clear keeps it a flicker-free top(1)-style refresh
        out.write("\x1b[H\x1b[2J" if out.isatty() else "")
        out.write(time.strftime("qrtop  %H:%M:%S") + f"  ({len(rows)} "
                  "gateway(s))\n" + frame + "\n")
        out.flush()
        prev = {r["gateway"]: r for r in rows if r.get("reachable")}
        n += 1
        if iterations is not None and n >= iterations:
            break
        time.sleep(interval)


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n\n")[0])
    ap.add_argument("endpoints", nargs="*",
                    help="gateway telemetry endpoints: host:port or "
                         "name=host:port")
    ap.add_argument("--fleet", action="append", default=None,
                    help="router telemetry host:port — discover gateway "
                         "endpoints from its /fleet view; repeat once per "
                         "replica (HA control plane): discovery falls "
                         "back across them and each renders a ROLE row")
    ap.add_argument("--interval", type=float, default=2.0,
                    help="poll interval (seconds) in live mode")
    ap.add_argument("--iterations", type=int, default=None,
                    help="stop after N frames (default: run until ^C)")
    ap.add_argument("--snapshot", action="store_true",
                    help="one poll, JSON document to stdout (CI artifact "
                         "mode)")
    ap.add_argument("--out", default=None,
                    help="with --snapshot: also write the JSON here")
    args = ap.parse_args(argv)

    endpoints: dict[str, str] = {}
    routers: dict[str, str] = {}
    if args.fleet:
        gw_eps, routers = discover_fleet(list(args.fleet))
        endpoints.update(gw_eps)
    for i, spec in enumerate(args.endpoints):
        name, _, base = spec.rpartition("=")
        endpoints[name or f"gw{i}"] = base
    if not endpoints:
        ap.error("no endpoints (pass host:port args or --fleet)")

    if args.snapshot:
        doc = snapshot_endpoints(endpoints, routers=routers or None)
        line = json.dumps(doc, indent=2, sort_keys=True)
        print(line)
        if args.out:
            with open(args.out, "w") as f:
                f.write(line + "\n")
        unreachable = [g for g, row in doc["gateways"].items()
                       if not row.get("reachable")]
        return 1 if len(unreachable) == len(doc["gateways"]) else 0

    try:
        live_loop(endpoints, args.interval, args.iterations,
                  routers=routers or None)
    except KeyboardInterrupt:
        pass
    return 0


if __name__ == "__main__":
    sys.exit(main())
