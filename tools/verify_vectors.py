"""Check official-format KAT files and report per-family interop status.

The reference inherits liboqs's interop by construction (vendor/oqs.py's
binary passes NIST KATs upstream); this framework has no egress to fetch the
official files, so the correctness anchor is layered (docs/correctness.md):
self-generated cross-implementation vectors now, plus THIS tool — drop
official ACVP JSON or NIST PQCgenKAT ``.rsp`` files into ``tests/vectors/``
and it checks every family against the pure-Python oracles and reports, per
family, whether the anchor is an official file or still a generated fixture.

Formats understood (filename selects the checker):

  acvp_mlkem*.json    ACVP ML-KEM keyGen/encap/decap (d/z/ek/dk, ek/m/c/k)
  acvp_mldsa*.json    ACVP ML-DSA keyGen/sigGen/sigVer (internal interface)
  acvp_slhdsa*.json   ACVP SLH-DSA keyGen/sigGen/sigVer (internal interface)
  *mlkem*.rsp         PQCgenKAT stanzas; DRBG stream d||z, encaps m
                      (round-3 *Kyber* KATs are NOT accepted: Kyber's
                      encaps/KDF differ from final FIPS 203)
  *frodo*.rsp         PQCgenKAT stanzas; DRBG stream s||seedSE||z(16), mu
  *hqc*.rsp           stanzas with the reconstructed official round-4 seam
                      (sk_seed||sigma||pk_seed, m||salt); on an official-
                      file mismatch a diagnosis decision tree names which
                      seam assumption the file refutes (correctness.md)

Usage: python -m tools.verify_vectors [--vectors-dir DIR] [--json]
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

from quantum_resistant_p2p_tpu.pyref import (  # noqa: E402
    frodo_ref,
    hqc_ref,
    mldsa_ref,
    mlkem_ref,
    slhdsa_ref,
)
from quantum_resistant_p2p_tpu.utils.ctr_drbg import CtrDrbg  # noqa: E402

VECTOR_DIR = Path(__file__).resolve().parent.parent / "tests" / "vectors"


def _acvp_tests(data: dict):
    for group in data.get("testGroups", []):
        meta = {k: v for k, v in group.items() if k != "tests"}
        for t in group.get("tests", []):
            yield {**meta, **t}


def _rsp_stanzas(text: str):
    rec: dict = {}
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            if rec:
                yield rec
                rec = {}
            continue
        if "=" in line:
            k, _, v = line.partition("=")
            rec[k.strip()] = v.strip()
    if rec:
        yield rec


def _eq(name: str, actual: bytes, expected_hex: str, errors: list[str]) -> int:
    if actual.hex() != expected_hex.lower():
        errors.append(f"{name} mismatch")
        return 0
    return 1


# -- ACVP JSON checkers ------------------------------------------------------


def _param_for(t: dict, data: dict, table: dict, default: str, aliases=None):
    """Resolve the parameter set for one ACVP test: the per-group
    ``parameterSet`` (official files use a family-level "algorithm" with the
    concrete set per group) wins over the file-level "algorithm"."""
    name = t.get("parameterSet") or data.get("algorithm") or default
    if aliases and name in aliases:
        name = aliases[name]
    return table[name if name in table else default]


def check_acvp_mlkem(data: dict) -> tuple[int, int, list[str]]:
    n = ok = 0
    errors: list[str] = []
    for t in _acvp_tests(data):
        p = _param_for(t, data, mlkem_ref.PARAMS, "ML-KEM-768")
        if "d" in t and "z" in t:
            n += 1
            ek, dk = mlkem_ref.keygen(p, bytes.fromhex(t["d"]), bytes.fromhex(t["z"]))
            ok += _eq("ek", ek, t["ek"], errors) & _eq("dk", dk, t["dk"], errors)
        if "m" in t and "ek" in t and "c" in t:
            n += 1
            k, c = mlkem_ref.encaps(p, bytes.fromhex(t["ek"]), bytes.fromhex(t["m"]))
            ok += _eq("c", c, t["c"], errors) & _eq("k", k, t["k"], errors)
        if "dk" in t and "c" in t and "d" not in t:
            n += 1
            k = mlkem_ref.decaps(p, bytes.fromhex(t["dk"]), bytes.fromhex(t["c"]))
            ok += _eq("k", k, t["k"], errors)
    return n, ok, errors


def check_acvp_mldsa(data: dict) -> tuple[int, int, list[str]]:
    n = ok = 0
    errors: list[str] = []
    for t in _acvp_tests(data):
        p = _param_for(t, data, mldsa_ref.PARAMS, "ML-DSA-65")
        if "seed" in t and "pk" in t:  # keyGen
            n += 1
            pk, sk = mldsa_ref.keygen(p, bytes.fromhex(t["seed"]))
            ok += _eq("pk", pk, t["pk"], errors) & _eq("sk", sk, t["sk"], errors)
        elif "sk" in t and "message" in t and "signature" in t:  # sigGen internal
            n += 1
            rnd = bytes.fromhex(t.get("rnd", "00" * 32))
            sig = mldsa_ref.sign_internal(
                p, bytes.fromhex(t["sk"]), bytes.fromhex(t["message"]), rnd
            )
            ok += _eq("signature", sig, t["signature"], errors)
        elif "pk" in t and "message" in t and "signature" in t:  # sigVer internal
            n += 1
            passed = mldsa_ref.verify_internal(
                p, bytes.fromhex(t["pk"]), bytes.fromhex(t["message"]),
                bytes.fromhex(t["signature"]),
            )
            if passed == t.get("testPassed", True):
                ok += 1
            else:
                errors.append("sigVer testPassed mismatch")
    return n, ok, errors


#: official ACVP SLH-DSA names -> this repo's registry names
_SLH_ALIASES = {
    f"SLH-DSA-SHA2-{size}{v}": f"SPHINCS+-SHA2-{size}{v}-simple"
    for size in (128, 192, 256) for v in ("s", "f")
}


def check_acvp_slhdsa(data: dict) -> tuple[int, int, list[str]]:
    n = ok = 0
    errors: list[str] = []
    for t in _acvp_tests(data):
        p = _param_for(t, data, slhdsa_ref.PARAMS,
                       "SPHINCS+-SHA2-128f-simple", _SLH_ALIASES)
        if "skSeed" in t:  # keyGen
            n += 1
            pk, sk = slhdsa_ref.keygen(
                p, bytes.fromhex(t["skSeed"]), bytes.fromhex(t["skPrf"]),
                bytes.fromhex(t["pkSeed"]),
            )
            ok += _eq("pk", pk, t["pk"], errors) & _eq("sk", sk, t["sk"], errors)
        elif "sk" in t and "message" in t and "signature" in t:  # sigGen internal
            n += 1
            sig = slhdsa_ref.sign_internal(
                p, bytes.fromhex(t["message"]), bytes.fromhex(t["sk"]), None
            )
            ok += _eq("signature", sig, t["signature"], errors)
        elif "pk" in t and "message" in t and "signature" in t:  # sigVer
            n += 1
            passed = slhdsa_ref.verify_internal(
                p, bytes.fromhex(t["message"]), bytes.fromhex(t["signature"]),
                bytes.fromhex(t["pk"]),
            )
            if passed == t.get("testPassed", True):
                ok += 1
            else:
                errors.append("sigVer testPassed mismatch")
    return n, ok, errors


# -- PQCgenKAT .rsp checkers -------------------------------------------------
#
# PQCgenKAT_kem.c seeds an AES-256 CTR-DRBG per stanza and the algorithm's
# randombytes() calls consume its stream in a fixed order; the split below is
# each family's documented order (docs/correctness.md "DRBG seam" notes).


def _algo_from_rsp(fname: str, table: dict[str, str], default: str) -> str:
    low = fname.lower()
    for key, algo in table.items():
        if key in low:
            return algo
    return default


def check_rsp_mlkem(text: str, fname: str) -> tuple[int, int, list[str]]:
    algo = _algo_from_rsp(
        fname,
        {"512": "ML-KEM-512", "768": "ML-KEM-768", "1024": "ML-KEM-1024"},
        "ML-KEM-768",
    )
    p = mlkem_ref.PARAMS[algo]
    n = ok = 0
    errors: list[str] = []
    for rec in _rsp_stanzas(text):
        if "seed" not in rec:
            continue
        n += 1
        drbg = CtrDrbg(bytes.fromhex(rec["seed"]))
        d, z = drbg.random_bytes(32), drbg.random_bytes(32)
        ek, dk = mlkem_ref.keygen(p, d, z)
        m = drbg.random_bytes(32)
        k, c = mlkem_ref.encaps(p, ek, m)
        good = 1
        if "pk" in rec:
            good &= _eq("pk", ek, rec["pk"], errors)
        if "sk" in rec:
            good &= _eq("sk", dk, rec["sk"], errors)
        if "ct" in rec:
            good &= _eq("ct", c, rec["ct"], errors)
        if "ss" in rec:
            good &= _eq("ss", k, rec["ss"], errors)
        ok += good
    return n, ok, errors


def check_rsp_frodo(text: str, fname: str) -> tuple[int, int, list[str]]:
    algo = _algo_from_rsp(
        fname,
        {
            "640-aes": "FrodoKEM-640-AES", "640aes": "FrodoKEM-640-AES",
            "640-shake": "FrodoKEM-640-SHAKE", "640shake": "FrodoKEM-640-SHAKE",
            "976-aes": "FrodoKEM-976-AES", "976aes": "FrodoKEM-976-AES",
            "976-shake": "FrodoKEM-976-SHAKE", "976shake": "FrodoKEM-976-SHAKE",
            "1344-aes": "FrodoKEM-1344-AES", "1344aes": "FrodoKEM-1344-AES",
            "1344-shake": "FrodoKEM-1344-SHAKE", "1344shake": "FrodoKEM-1344-SHAKE",
        },
        "FrodoKEM-640-SHAKE",
    )
    p = frodo_ref.PARAMS[algo]
    n = ok = 0
    errors: list[str] = []
    for rec in _rsp_stanzas(text):
        if "seed" not in rec:
            continue
        n += 1
        drbg = CtrDrbg(bytes.fromhex(rec["seed"]))
        # crypto_kem_keypair: one randombytes(2*CRYPTO_BYTES + BYTES_SEED_A)
        # call, split s || seedSE || z (z is 16 bytes at every level).
        r = drbg.random_bytes(2 * p.len_sec + 16)
        s, seed_se, z = r[: p.len_sec], r[p.len_sec : 2 * p.len_sec], r[2 * p.len_sec :]
        pk, sk = frodo_ref.keygen(p, s, seed_se, z)
        mu = drbg.random_bytes(p.len_sec)
        ct, ss = frodo_ref.encaps(p, pk, mu)
        good = 1
        if "pk" in rec:
            good &= _eq("pk", pk, rec["pk"], errors)
        if "sk" in rec:
            good &= _eq("sk", sk, rec["sk"], errors)
        if "ct" in rec:
            good &= _eq("ct", ct, rec["ct"], errors)
        if "ss" in rec:
            good &= _eq("ss", ss, rec["ss"], errors)
        ok += good
    return n, ok, errors


def _hqc_keygen_order(p, sk_seed: bytes, sigma: bytes, pk_seed: bytes,
                      x_first: bool) -> bytes:
    """pk under either sk-expander draw order (diagnosis helper).

    x_first=True is the ROUND-3 order; the implemented round-4 order draws
    y first (hqc_ref.keygen), corroborated by official round-4 decaps
    regenerating ONLY y with a single first draw."""
    ctx = hqc_ref.SeedExpander(sk_seed)
    a = hqc_ref.sample_fixed_weight(p, ctx, p.w)
    b = hqc_ref.sample_fixed_weight(p, ctx, p.w)
    x, y = (a, b) if x_first else (b, a)
    h = hqc_ref.sample_random_vector(p, hqc_ref.SeedExpander(pk_seed))
    s = x ^ hqc_ref.cyclic_mul(p, h, y)
    return pk_seed + s.to_bytes(p.n_bytes, "little")


def _hqc_encrypt_order(p, pk: bytes, m: bytes, theta: bytes,
                       order: tuple[str, str, str]) -> tuple[int, int]:
    """(u, v) with the three theta-expander draws permuted (diagnosis)."""
    s = int.from_bytes(pk[40:], "little")
    h = hqc_ref.sample_random_vector(p, hqc_ref.SeedExpander(pk[:40]))
    ctx = hqc_ref.SeedExpander(theta)
    d = {name: hqc_ref.sample_fixed_weight(p, ctx, p.wr) for name in order}
    u = d["r1"] ^ hqc_ref.cyclic_mul(p, h, d["r2"])
    t = hqc_ref.code_encode(p, m) ^ hqc_ref.cyclic_mul(p, s, d["r2"]) ^ d["e"]
    return u, t & ((1 << (p.n1 * p.n2)) - 1)


def _diagnose_hqc(p, seed: bytes, rec: dict) -> list[str]:
    """Decision tree: which documented HQC seam assumption does a failing
    official stanza actually refute?  (docs/correctness.md §HQC seam —
    each branch names the divergence point and, where the alternatives are
    enumerable, which alternative DOES reproduce the official bytes.)"""
    notes: list[str] = []
    lens = {"sk_seed": 40, "sigma": p.k, "pk_seed": 40}
    # Candidate randombytes() call orders inside keygen.  NOT modeled as
    # offsets into one stream: each CTR-DRBG call pads to the AES block
    # and rekeys, so distinct call sequences give unrelated bytes.
    candidates = {
        "implemented order sk_seed||sigma||pk_seed":
            ("sk_seed", "sigma", "pk_seed"),
        "order sk_seed||pk_seed||sigma": ("sk_seed", "pk_seed", "sigma"),
        "pk_seed drawn FIRST (order pk_seed||sk_seed||sigma)":
            ("pk_seed", "sk_seed", "sigma"),
    }

    def draws_for(names: tuple[str, ...]) -> dict[str, bytes]:
        drbg = CtrDrbg(seed)
        out = {name: drbg.random_bytes(lens[name]) for name in names}
        out["m"], out["salt"] = drbg.random_bytes(p.k), drbg.random_bytes(16)
        return out

    keygen_exact = False
    impl = draws_for(candidates["implemented order sk_seed||sigma||pk_seed"])
    if "pk" in rec:
        pk_exp = bytes.fromhex(rec["pk"])
        hits = [lab for lab, names in candidates.items()
                if pk_exp[:40] == draws_for(names)["pk_seed"]]
        if not hits:
            notes.append(
                "pk[0:40] (pk_seed) matches NO candidate randombytes order — "
                "the DRBG itself or the 40-byte seed length assumption is "
                "wrong for this file")
            return notes
        notes.append(f"pk_seed position confirmed: {hits[0]}")
        if "implemented" not in hits[0]:
            return notes  # draw order refuted; everything downstream shifts
        sk_seed, sigma, pk_seed = impl["sk_seed"], impl["sigma"], impl["pk_seed"]
        if pk_exp != _hqc_keygen_order(p, sk_seed, sigma, pk_seed, x_first=False):
            if pk_exp == _hqc_keygen_order(p, sk_seed, sigma, pk_seed, x_first=True):
                notes.append(
                    "pk body matches the ROUND-3 sk-draw order (x before y) — "
                    "flip hqc_ref.keygen/kem.hqc keygen+decaps draw order")
            else:
                notes.append(
                    "pk_seed position right but s = x + h*y differs under BOTH "
                    "y-first and x-first orders — the fixed-weight sampler, "
                    "vect_set_random, or the cyclic product diverges")
            return notes
        notes.append("full pk reproduced — keygen seam is byte-exact")
        keygen_exact = True
    if "sk" in rec and "pk" in rec:
        sk_exp = bytes.fromhex(rec["sk"])
        ours = impl["sk_seed"] + impl["sigma"] + bytes.fromhex(rec["pk"])
        if sk_exp != ours:
            if sk_exp[:40] != impl["sk_seed"]:
                notes.append("sk[0:40] is not the first DRBG draw — sk_seed "
                             "position assumption refuted")
            elif sk_exp[40:40 + p.k] != impl["sigma"]:
                notes.append("sk sigma bytes are not DRBG draw #2 — sigma "
                             "position refuted (drawn after pk_seed?)")
            else:
                notes.append("sk serialization layout differs (not "
                             "sk_seed||sigma||pk)")
    if "ct" in rec and keygen_exact:
        ct_exp = bytes.fromhex(rec["ct"])
        pk_b = bytes.fromhex(rec["pk"])
        m, salt = impl["m"], impl["salt"]
        if ct_exp[-16:] != salt:
            notes.append("ct salt tail is not encaps DRBG draw #2 — the "
                         "m||salt draw order/lengths assumption is refuted")
            return notes
        for theta_lab, theta in (
            ("G(m||pk[0:32]||salt) (implemented)",
             hqc_ref._hash_g(m + pk_b[:32] + salt)),
            ("G(m||pk[0:40]||salt)", hqc_ref._hash_g(m + pk_b[:40] + salt)),
        ):
            for order in (("r2", "e", "r1"), ("r1", "r2", "e"), ("r2", "r1", "e"),
                          ("r1", "e", "r2"), ("e", "r2", "r1"), ("e", "r1", "r2")):
                u, v = _hqc_encrypt_order(p, pk_b, m, theta, order)
                if (u.to_bytes(p.n_bytes, "little")
                        + v.to_bytes(p.n1n2_bytes, "little") + salt) == ct_exp:
                    lab = f"theta={theta_lab}, draw order {'>'.join(order)}"
                    if "implemented" in theta_lab and order == ("r2", "e", "r1"):
                        notes.append("full ct reproduced — encaps seam is "
                                     "byte-exact")
                        notes += _diagnose_hqc_ss(p, m, salt, ct_exp, rec)
                    else:
                        notes.append(f"ct reproduced by the VARIANT {lab} — "
                                     "adopt it in hqc_ref._encrypt/encaps")
                    return notes
        notes.append("ct matches no (theta, draw-order) variant — the "
                     "divergence is inside sampling or the code/cyclic math, "
                     "not the enumerated seam points")
    return notes


def _diagnose_hqc_ss(p, m: bytes, salt: bytes, ct_exp: bytes,
                     rec: dict) -> list[str]:
    """ss-binding diagnosis, reached once keygen AND ct are byte-exact:
    an ss-only mismatch means the K construction itself diverges."""
    if "ss" not in rec:
        return []
    import hashlib as _hashlib

    u_b, v_b = ct_exp[:p.n_bytes], ct_exp[p.n_bytes:-16]
    ss_exp = bytes.fromhex(rec["ss"])
    if ss_exp == hqc_ref._hash_k(m + u_b + v_b):
        return ["full ss reproduced — K binding is byte-exact"]
    for lab, cand in (
        ("K(m||u||v||salt)", hqc_ref._hash_k(m + u_b + v_b + salt)),
        ("K(m||ct) with salt included", hqc_ref._hash_k(m + ct_exp)),
        ("K with domain byte 0x05",
         _hashlib.shake_256(m + u_b + v_b + b"\x05").digest(64)),
        ("K without a domain byte",
         _hashlib.shake_256(m + u_b + v_b).digest(64)),
    ):
        if ss_exp == cand:
            return [f"ss reproduced by the VARIANT {lab} — adopt it in "
                    "hqc_ref.encaps/decaps"]
    return ["ss matches no enumerated K-binding variant — the K "
            "construction diverges beyond the enumerated points"]


def check_rsp_hqc(text: str, fname: str) -> tuple[int, int, list[str]]:
    algo = _algo_from_rsp(
        fname, {"128": "HQC-128", "192": "HQC-192", "256": "HQC-256"}, "HQC-128"
    )
    p = hqc_ref.PARAMS[algo]
    n = ok = 0
    errors: list[str] = []
    diagnosed = False
    for rec in _rsp_stanzas(text):
        if "seed" not in rec:
            continue
        n += 1
        drbg = CtrDrbg(bytes.fromhex(rec["seed"]))
        # Implemented seam (pyref.hqc_ref docstring + docs/correctness.md
        # §HQC seam): reconstructed from the official round-4 reference's
        # randombytes/seedexpander call order; unverified offline.  On the
        # first failing stanza of an official file, _diagnose_hqc reports
        # exactly which seam assumption the file refutes.
        sk_seed, sigma, pk_seed = (
            drbg.random_bytes(40), drbg.random_bytes(p.k), drbg.random_bytes(40)
        )
        pk, sk = hqc_ref.keygen(p, sk_seed, sigma, pk_seed)
        m, salt = drbg.random_bytes(p.k), drbg.random_bytes(16)
        ct, ss = hqc_ref.encaps(p, pk, m, salt)
        good = 1
        if "pk" in rec:
            good &= _eq("pk", pk, rec["pk"], errors)
        if "sk" in rec:
            good &= _eq("sk", sk, rec["sk"], errors)
        if "ct" in rec:
            good &= _eq("ct", ct, rec["ct"], errors)
        if "ss" in rec:
            good &= _eq("ss", ss, rec["ss"], errors)
        if not good and not diagnosed:
            notes = _diagnose_hqc(p, bytes.fromhex(rec["seed"]), rec)
            errors.extend(f"diagnosis: {note}" for note in notes)
            # only consume the single diagnosis slot when something was
            # actually diagnosable (a pk-less stanza yields no notes)
            diagnosed = bool(notes)
        ok += good
    return n, ok, errors


# -- discovery + report ------------------------------------------------------

FAMILY_PATTERNS = [
    ("ML-KEM", "acvp_mlkem*.json", "acvp", check_acvp_mlkem),
    ("ML-DSA", "acvp_mldsa*.json", "acvp", check_acvp_mldsa),
    ("SLH-DSA", "acvp_slhdsa*.json", "acvp", check_acvp_slhdsa),
    # NOTE: no "*kyber*.rsp" pattern on purpose — round-3 Kyber KATs cannot
    # match FIPS 203 ML-KEM (different encaps hashing / KDF); routing them
    # here would report a spurious FAIL.
    ("ML-KEM", "*mlkem*.rsp", "rsp", check_rsp_mlkem),
    ("FrodoKEM", "*frodo*.rsp", "rsp", check_rsp_frodo),
    ("HQC", "*hqc*.rsp", "rsp", check_rsp_hqc),
]

FAMILIES = ["ML-KEM", "ML-DSA", "SLH-DSA", "FrodoKEM", "HQC"]

#: families whose official .rsp randomness seam is documented as NOT
#: reproduced (docs/correctness.md): official-file mismatches are expected
#: and reported as a distinct status, not a hard FAIL
EXPECTED_OFFICIAL_FAIL = {"HQC"}


def _is_fixture(path: Path) -> bool:
    if "fixture" in path.name.lower():
        return True
    head = path.read_text()[:512]
    return "qrp2p" in head.lower()


def verify_directory(vector_dir: Path) -> dict:
    per_family: dict[str, dict] = {
        f: {"files": [], "vectors": 0, "passed": 0, "official_files": 0,
            "fixture_failures": 0, "official_failures": 0, "errors": []}
        for f in FAMILIES
    }
    seen: set[Path] = set()
    for family, pattern, kind, checker in FAMILY_PATTERNS:
        for path in sorted(vector_dir.glob(pattern)):
            if path in seen:
                continue
            seen.add(path)
            if kind == "acvp":
                n, ok, errors = checker(json.loads(path.read_text()))
            else:
                n, ok, errors = checker(path.read_text(), path.name)
            fixture = _is_fixture(path)
            fam = per_family[family]
            fam["files"].append(path.name)
            fam["vectors"] += n
            fam["passed"] += ok
            fam["errors"] += [f"{path.name}: {e}" for e in errors[:5]]
            if fixture:
                fam["fixture_failures"] += n - ok
            else:
                fam["official_files"] += 1
                fam["official_failures"] += n - ok
    for family, fam in per_family.items():
        if not fam["files"]:
            fam["status"] = "no files"
        elif fam["fixture_failures"]:
            fam["status"] = "FAIL"
        elif fam["official_failures"]:
            # A failing official file is a hard FAIL unless the family's
            # seam is documented as unverified (expected until confirmed).
            fam["status"] = (
                "official vectors DO NOT match — see the divergence "
                "diagnosis in errors (docs/correctness.md §HQC seam)"
                if family in EXPECTED_OFFICIAL_FAIL
                else "FAIL"
            )
        elif fam["official_files"]:
            fam["status"] = "official vectors pass"
        else:
            fam["status"] = "fixtures pass (official files not yet dropped in)"
    return per_family


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--vectors-dir", default=str(VECTOR_DIR))
    ap.add_argument("--json", action="store_true", help="machine-readable output")
    args = ap.parse_args(argv)
    report = verify_directory(Path(args.vectors_dir))
    if args.json:
        print(json.dumps(report))
    else:
        for family, fam in report.items():
            print(f"{family:10s} {fam['status']:45s} "
                  f"{fam['passed']}/{fam['vectors']} vectors, "
                  f"files: {', '.join(fam['files']) or '-'}")
            for e in fam["errors"]:
                print(f"           ! {e}")
    bad = any(f["status"] == "FAIL" for f in report.values())
    return 1 if bad else 0


if __name__ == "__main__":
    sys.exit(main())
