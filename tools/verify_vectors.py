"""Check official-format KAT files and report per-family interop status.

The reference inherits liboqs's interop by construction (vendor/oqs.py's
binary passes NIST KATs upstream); this framework has no egress to fetch the
official files, so the correctness anchor is layered (docs/correctness.md):
self-generated cross-implementation vectors now, plus THIS tool — drop
official ACVP JSON or NIST PQCgenKAT ``.rsp`` files into ``tests/vectors/``
and it checks every family against the pure-Python oracles and reports, per
family, whether the anchor is an official file or still a generated fixture.

Formats understood (filename selects the checker):

  acvp_mlkem*.json    ACVP ML-KEM keyGen/encap/decap (d/z/ek/dk, ek/m/c/k)
  acvp_mldsa*.json    ACVP ML-DSA keyGen/sigGen/sigVer (internal interface)
  acvp_slhdsa*.json   ACVP SLH-DSA keyGen/sigGen/sigVer (internal interface)
  *mlkem*.rsp         PQCgenKAT stanzas; DRBG stream d||z, encaps m
                      (round-3 *Kyber* KATs are NOT accepted: Kyber's
                      encaps/KDF differ from final FIPS 203)
  *frodo*.rsp         PQCgenKAT stanzas; DRBG stream s||seedSE||z(16), mu
  *hqc*.rsp           stanzas with THIS framework's documented seam
                      (sk_seed||sigma||pk_seed, m||salt) — HQC's official
                      randombytes order is not reproduced (correctness.md)

Usage: python -m tools.verify_vectors [--vectors-dir DIR] [--json]
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

from quantum_resistant_p2p_tpu.pyref import (  # noqa: E402
    frodo_ref,
    hqc_ref,
    mldsa_ref,
    mlkem_ref,
    slhdsa_ref,
)
from quantum_resistant_p2p_tpu.utils.ctr_drbg import CtrDrbg  # noqa: E402

VECTOR_DIR = Path(__file__).resolve().parent.parent / "tests" / "vectors"


def _acvp_tests(data: dict):
    for group in data.get("testGroups", []):
        meta = {k: v for k, v in group.items() if k != "tests"}
        for t in group.get("tests", []):
            yield {**meta, **t}


def _rsp_stanzas(text: str):
    rec: dict = {}
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            if rec:
                yield rec
                rec = {}
            continue
        if "=" in line:
            k, _, v = line.partition("=")
            rec[k.strip()] = v.strip()
    if rec:
        yield rec


def _eq(name: str, actual: bytes, expected_hex: str, errors: list[str]) -> int:
    if actual.hex() != expected_hex.lower():
        errors.append(f"{name} mismatch")
        return 0
    return 1


# -- ACVP JSON checkers ------------------------------------------------------


def _param_for(t: dict, data: dict, table: dict, default: str, aliases=None):
    """Resolve the parameter set for one ACVP test: the per-group
    ``parameterSet`` (official files use a family-level "algorithm" with the
    concrete set per group) wins over the file-level "algorithm"."""
    name = t.get("parameterSet") or data.get("algorithm") or default
    if aliases and name in aliases:
        name = aliases[name]
    return table[name if name in table else default]


def check_acvp_mlkem(data: dict) -> tuple[int, int, list[str]]:
    n = ok = 0
    errors: list[str] = []
    for t in _acvp_tests(data):
        p = _param_for(t, data, mlkem_ref.PARAMS, "ML-KEM-768")
        if "d" in t and "z" in t:
            n += 1
            ek, dk = mlkem_ref.keygen(p, bytes.fromhex(t["d"]), bytes.fromhex(t["z"]))
            ok += _eq("ek", ek, t["ek"], errors) & _eq("dk", dk, t["dk"], errors)
        if "m" in t and "ek" in t and "c" in t:
            n += 1
            k, c = mlkem_ref.encaps(p, bytes.fromhex(t["ek"]), bytes.fromhex(t["m"]))
            ok += _eq("c", c, t["c"], errors) & _eq("k", k, t["k"], errors)
        if "dk" in t and "c" in t and "d" not in t:
            n += 1
            k = mlkem_ref.decaps(p, bytes.fromhex(t["dk"]), bytes.fromhex(t["c"]))
            ok += _eq("k", k, t["k"], errors)
    return n, ok, errors


def check_acvp_mldsa(data: dict) -> tuple[int, int, list[str]]:
    n = ok = 0
    errors: list[str] = []
    for t in _acvp_tests(data):
        p = _param_for(t, data, mldsa_ref.PARAMS, "ML-DSA-65")
        if "seed" in t and "pk" in t:  # keyGen
            n += 1
            pk, sk = mldsa_ref.keygen(p, bytes.fromhex(t["seed"]))
            ok += _eq("pk", pk, t["pk"], errors) & _eq("sk", sk, t["sk"], errors)
        elif "sk" in t and "message" in t and "signature" in t:  # sigGen internal
            n += 1
            rnd = bytes.fromhex(t.get("rnd", "00" * 32))
            sig = mldsa_ref.sign_internal(
                p, bytes.fromhex(t["sk"]), bytes.fromhex(t["message"]), rnd
            )
            ok += _eq("signature", sig, t["signature"], errors)
        elif "pk" in t and "message" in t and "signature" in t:  # sigVer internal
            n += 1
            passed = mldsa_ref.verify_internal(
                p, bytes.fromhex(t["pk"]), bytes.fromhex(t["message"]),
                bytes.fromhex(t["signature"]),
            )
            if passed == t.get("testPassed", True):
                ok += 1
            else:
                errors.append("sigVer testPassed mismatch")
    return n, ok, errors


#: official ACVP SLH-DSA names -> this repo's registry names
_SLH_ALIASES = {
    f"SLH-DSA-SHA2-{size}{v}": f"SPHINCS+-SHA2-{size}{v}-simple"
    for size in (128, 192, 256) for v in ("s", "f")
}


def check_acvp_slhdsa(data: dict) -> tuple[int, int, list[str]]:
    n = ok = 0
    errors: list[str] = []
    for t in _acvp_tests(data):
        p = _param_for(t, data, slhdsa_ref.PARAMS,
                       "SPHINCS+-SHA2-128f-simple", _SLH_ALIASES)
        if "skSeed" in t:  # keyGen
            n += 1
            pk, sk = slhdsa_ref.keygen(
                p, bytes.fromhex(t["skSeed"]), bytes.fromhex(t["skPrf"]),
                bytes.fromhex(t["pkSeed"]),
            )
            ok += _eq("pk", pk, t["pk"], errors) & _eq("sk", sk, t["sk"], errors)
        elif "sk" in t and "message" in t and "signature" in t:  # sigGen internal
            n += 1
            sig = slhdsa_ref.sign_internal(
                p, bytes.fromhex(t["message"]), bytes.fromhex(t["sk"]), None
            )
            ok += _eq("signature", sig, t["signature"], errors)
        elif "pk" in t and "message" in t and "signature" in t:  # sigVer
            n += 1
            passed = slhdsa_ref.verify_internal(
                p, bytes.fromhex(t["message"]), bytes.fromhex(t["signature"]),
                bytes.fromhex(t["pk"]),
            )
            if passed == t.get("testPassed", True):
                ok += 1
            else:
                errors.append("sigVer testPassed mismatch")
    return n, ok, errors


# -- PQCgenKAT .rsp checkers -------------------------------------------------
#
# PQCgenKAT_kem.c seeds an AES-256 CTR-DRBG per stanza and the algorithm's
# randombytes() calls consume its stream in a fixed order; the split below is
# each family's documented order (docs/correctness.md "DRBG seam" notes).


def _algo_from_rsp(fname: str, table: dict[str, str], default: str) -> str:
    low = fname.lower()
    for key, algo in table.items():
        if key in low:
            return algo
    return default


def check_rsp_mlkem(text: str, fname: str) -> tuple[int, int, list[str]]:
    algo = _algo_from_rsp(
        fname,
        {"512": "ML-KEM-512", "768": "ML-KEM-768", "1024": "ML-KEM-1024"},
        "ML-KEM-768",
    )
    p = mlkem_ref.PARAMS[algo]
    n = ok = 0
    errors: list[str] = []
    for rec in _rsp_stanzas(text):
        if "seed" not in rec:
            continue
        n += 1
        drbg = CtrDrbg(bytes.fromhex(rec["seed"]))
        d, z = drbg.random_bytes(32), drbg.random_bytes(32)
        ek, dk = mlkem_ref.keygen(p, d, z)
        m = drbg.random_bytes(32)
        k, c = mlkem_ref.encaps(p, ek, m)
        good = 1
        if "pk" in rec:
            good &= _eq("pk", ek, rec["pk"], errors)
        if "sk" in rec:
            good &= _eq("sk", dk, rec["sk"], errors)
        if "ct" in rec:
            good &= _eq("ct", c, rec["ct"], errors)
        if "ss" in rec:
            good &= _eq("ss", k, rec["ss"], errors)
        ok += good
    return n, ok, errors


def check_rsp_frodo(text: str, fname: str) -> tuple[int, int, list[str]]:
    algo = _algo_from_rsp(
        fname,
        {
            "640-aes": "FrodoKEM-640-AES", "640aes": "FrodoKEM-640-AES",
            "640-shake": "FrodoKEM-640-SHAKE", "640shake": "FrodoKEM-640-SHAKE",
            "976-aes": "FrodoKEM-976-AES", "976aes": "FrodoKEM-976-AES",
            "976-shake": "FrodoKEM-976-SHAKE", "976shake": "FrodoKEM-976-SHAKE",
            "1344-aes": "FrodoKEM-1344-AES", "1344aes": "FrodoKEM-1344-AES",
            "1344-shake": "FrodoKEM-1344-SHAKE", "1344shake": "FrodoKEM-1344-SHAKE",
        },
        "FrodoKEM-640-SHAKE",
    )
    p = frodo_ref.PARAMS[algo]
    n = ok = 0
    errors: list[str] = []
    for rec in _rsp_stanzas(text):
        if "seed" not in rec:
            continue
        n += 1
        drbg = CtrDrbg(bytes.fromhex(rec["seed"]))
        # crypto_kem_keypair: one randombytes(2*CRYPTO_BYTES + BYTES_SEED_A)
        # call, split s || seedSE || z (z is 16 bytes at every level).
        r = drbg.random_bytes(2 * p.len_sec + 16)
        s, seed_se, z = r[: p.len_sec], r[p.len_sec : 2 * p.len_sec], r[2 * p.len_sec :]
        pk, sk = frodo_ref.keygen(p, s, seed_se, z)
        mu = drbg.random_bytes(p.len_sec)
        ct, ss = frodo_ref.encaps(p, pk, mu)
        good = 1
        if "pk" in rec:
            good &= _eq("pk", pk, rec["pk"], errors)
        if "sk" in rec:
            good &= _eq("sk", sk, rec["sk"], errors)
        if "ct" in rec:
            good &= _eq("ct", ct, rec["ct"], errors)
        if "ss" in rec:
            good &= _eq("ss", ss, rec["ss"], errors)
        ok += good
    return n, ok, errors


def check_rsp_hqc(text: str, fname: str) -> tuple[int, int, list[str]]:
    algo = _algo_from_rsp(
        fname, {"128": "HQC-128", "192": "HQC-192", "256": "HQC-256"}, "HQC-128"
    )
    p = hqc_ref.PARAMS[algo]
    n = ok = 0
    errors: list[str] = []
    for rec in _rsp_stanzas(text):
        if "seed" not in rec:
            continue
        n += 1
        drbg = CtrDrbg(bytes.fromhex(rec["seed"]))
        # THIS framework's seam (pyref.hqc_ref docstring): official HQC's
        # randombytes order is not reproduced, so official .rsp files are
        # expected to FAIL here — the report marks the family accordingly.
        sk_seed, sigma, pk_seed = (
            drbg.random_bytes(40), drbg.random_bytes(p.k), drbg.random_bytes(40)
        )
        pk, sk = hqc_ref.keygen(p, sk_seed, sigma, pk_seed)
        m, salt = drbg.random_bytes(p.k), drbg.random_bytes(16)
        ct, ss = hqc_ref.encaps(p, pk, m, salt)
        good = 1
        if "pk" in rec:
            good &= _eq("pk", pk, rec["pk"], errors)
        if "sk" in rec:
            good &= _eq("sk", sk, rec["sk"], errors)
        if "ct" in rec:
            good &= _eq("ct", ct, rec["ct"], errors)
        if "ss" in rec:
            good &= _eq("ss", ss, rec["ss"], errors)
        ok += good
    return n, ok, errors


# -- discovery + report ------------------------------------------------------

FAMILY_PATTERNS = [
    ("ML-KEM", "acvp_mlkem*.json", "acvp", check_acvp_mlkem),
    ("ML-DSA", "acvp_mldsa*.json", "acvp", check_acvp_mldsa),
    ("SLH-DSA", "acvp_slhdsa*.json", "acvp", check_acvp_slhdsa),
    # NOTE: no "*kyber*.rsp" pattern on purpose — round-3 Kyber KATs cannot
    # match FIPS 203 ML-KEM (different encaps hashing / KDF); routing them
    # here would report a spurious FAIL.
    ("ML-KEM", "*mlkem*.rsp", "rsp", check_rsp_mlkem),
    ("FrodoKEM", "*frodo*.rsp", "rsp", check_rsp_frodo),
    ("HQC", "*hqc*.rsp", "rsp", check_rsp_hqc),
]

FAMILIES = ["ML-KEM", "ML-DSA", "SLH-DSA", "FrodoKEM", "HQC"]

#: families whose official .rsp randomness seam is documented as NOT
#: reproduced (docs/correctness.md): official-file mismatches are expected
#: and reported as a distinct status, not a hard FAIL
EXPECTED_OFFICIAL_FAIL = {"HQC"}


def _is_fixture(path: Path) -> bool:
    if "fixture" in path.name.lower():
        return True
    head = path.read_text()[:512]
    return "qrp2p" in head.lower()


def verify_directory(vector_dir: Path) -> dict:
    per_family: dict[str, dict] = {
        f: {"files": [], "vectors": 0, "passed": 0, "official_files": 0,
            "fixture_failures": 0, "official_failures": 0, "errors": []}
        for f in FAMILIES
    }
    seen: set[Path] = set()
    for family, pattern, kind, checker in FAMILY_PATTERNS:
        for path in sorted(vector_dir.glob(pattern)):
            if path in seen:
                continue
            seen.add(path)
            if kind == "acvp":
                n, ok, errors = checker(json.loads(path.read_text()))
            else:
                n, ok, errors = checker(path.read_text(), path.name)
            fixture = _is_fixture(path)
            fam = per_family[family]
            fam["files"].append(path.name)
            fam["vectors"] += n
            fam["passed"] += ok
            fam["errors"] += [f"{path.name}: {e}" for e in errors[:5]]
            if fixture:
                fam["fixture_failures"] += n - ok
            else:
                fam["official_files"] += 1
                fam["official_failures"] += n - ok
    for family, fam in per_family.items():
        if not fam["files"]:
            fam["status"] = "no files"
        elif fam["fixture_failures"]:
            fam["status"] = "FAIL"
        elif fam["official_failures"]:
            # A failing official file is a hard FAIL unless the family's
            # seam is documented as unverified (expected until confirmed).
            fam["status"] = (
                "official vectors DO NOT match — seam unverified "
                "(expected for this family; docs/correctness.md)"
                if family in EXPECTED_OFFICIAL_FAIL
                else "FAIL"
            )
        elif fam["official_files"]:
            fam["status"] = "official vectors pass"
        else:
            fam["status"] = "fixtures pass (official files not yet dropped in)"
    return per_family


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--vectors-dir", default=str(VECTOR_DIR))
    ap.add_argument("--json", action="store_true", help="machine-readable output")
    args = ap.parse_args(argv)
    report = verify_directory(Path(args.vectors_dir))
    if args.json:
        print(json.dumps(report))
    else:
        for family, fam in report.items():
            print(f"{family:10s} {fam['status']:45s} "
                  f"{fam['passed']}/{fam['vectors']} vectors, "
                  f"files: {', '.join(fam['files']) or '-'}")
            for e in fam["errors"]:
                print(f"           ! {e}")
    bad = any(f["status"] == "FAIL" for f in report.values())
    return 1 if bad else 0


if __name__ == "__main__":
    sys.exit(main())
