"""Merge N nodes' span dumps into ONE chrome://tracing document.

The per-process exporter (``obs.trace.to_chrome_trace``) renders one
node's flame graph; a distributed handshake is only readable when BOTH
endpoints' spans sit on one timeline.  This tool takes span-dump
documents (``obs.trace.span_dump`` / ``export_spans``) — or bare record
lists — and emits a single trace-event JSON where:

* every NODE gets its own **process lane** (``pid`` + ``process_name``
  metadata), keyed by each record's ``node`` field (multi-node processes
  like the swarm benches attribute per record) falling back to the dump's
  own node name;
* every (node, thread) pair gets a **thread lane**;
* **cross-node parent edges** — a span whose parent lives on a different
  node, i.e. the propagated wire context (net/p2p_node.py ``_trace``) —
  are drawn as chrome flow arrows (``ph: s``/``f``) from the parent's
  span to the child's, so the responder's device dispatches hang visibly
  under the initiator's exchange;
* dumps from DIFFERENT processes are aligned onto one wall-clock
  timeline via each dump's (wall, mono) anchor pair; dumps without
  anchors (bare lists, same-process snapshots) share the raw timeline.

Load the output in chrome://tracing or https://ui.perfetto.dev.

Usage::

    python -m tools.trace_merge --out merged.json dump1.json dump2.json
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path
from typing import Any

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

_UNATTRIBUTED = "(unattributed)"


def _doc_records(doc: Any, default_node: str) -> list[tuple[dict, str, float]]:
    """-> (record, node, time_offset) triples for one input document."""
    if isinstance(doc, list):
        records, node, offset = doc, default_node, 0.0
    elif isinstance(doc, dict) and "spans" in doc:
        records = doc["spans"]
        node = str(doc.get("node") or default_node)
        # wall = mono + (wall_anchor - mono_anchor): shifts this dump's
        # tracer-relative timestamps onto the shared wall-clock timeline
        if doc.get("wall_anchor") is not None and doc.get("mono_anchor") is not None:
            offset = float(doc["wall_anchor"]) - float(doc["mono_anchor"])
        else:
            offset = 0.0
    else:
        raise ValueError(
            "input is neither a span-dump document nor a record list")
    return [(rec, str(rec.get("node") or node or _UNATTRIBUTED), offset)
            for rec in records]


def merge(docs: list[Any], node_names: list[str] | None = None) -> dict[str, Any]:
    """Merge span-dump documents into one chrome trace-event document."""
    triples: list[tuple[dict, str, float]] = []
    for i, doc in enumerate(docs):
        default = (node_names[i] if node_names and i < len(node_names)
                   else f"node{i}")
        triples.extend(_doc_records(doc, default))

    # stable lane assignment: process lanes in first-appearance order (the
    # initiator of the first span leads), thread lanes per node.  Assigned
    # up front so a flow arrow can target a parent lane that appears later
    # in record order than its child.
    pids: dict[str, int] = {}
    tids: dict[tuple[str, str], int] = {}
    for rec, node, _ in triples:
        pids.setdefault(node, len(pids) + 1)
        tkey = (node, rec["thread"])
        if tkey not in tids:
            tids[tkey] = sum(1 for k in tids if k[0] == node) + 1

    # span index for parent-edge resolution.  Keyed by (trace_id, span_id):
    # ids are tracer-tagged per process, so collisions mean the same span
    # exported twice — first occurrence wins.
    index: dict[tuple[str, str], tuple[str, float, dict]] = {}
    for rec, node, offset in triples:
        key = (rec["trace_id"], rec["span_id"])
        index.setdefault(key, (node, offset, rec))

    events: list[dict[str, Any]] = []
    t_min = min((rec["t0"] + off for rec, _, off in triples), default=0.0)
    flow_id = 0
    for rec, node, offset in triples:
        pid = pids[node]
        tid = tids[(node, rec["thread"])]
        ts = round((rec["t0"] + offset - t_min) * 1e6, 3)
        dur = round(rec["dur"] * 1e6, 3)
        events.append({
            "name": rec["name"],
            "ph": "X",
            "ts": ts,
            "dur": dur,
            "pid": pid,
            "tid": tid,
            "cat": rec["name"].split(".", 1)[0],
            "args": {
                "trace_id": rec["trace_id"],
                "span_id": rec["span_id"],
                "parent_id": rec["parent_id"],
                "node": node,
                **rec["attrs"],
            },
        })
        parent_id = rec.get("parent_id")
        if not parent_id:
            continue
        parent = index.get((rec["trace_id"], parent_id))
        if parent is None or parent[0] == node:
            continue  # same-lane nesting is visible without an arrow
        # cross-node edge (the propagated wire context): a flow arrow from
        # the remote parent span to this child span
        p_node, p_off, p_rec = parent
        flow_id += 1
        flow = {"name": "peer", "cat": "net", "id": flow_id}
        events.append({
            **flow, "ph": "s",
            "ts": round((p_rec["t0"] + p_off - t_min) * 1e6, 3),
            "pid": pids[p_node],
            "tid": tids[(p_node, p_rec["thread"])],
        })
        events.append({
            **flow, "ph": "f", "bp": "e", "ts": ts, "pid": pid, "tid": tid,
        })

    meta: list[dict[str, Any]] = []
    for node, pid in sorted(pids.items(), key=lambda kv: kv[1]):
        meta.append({"name": "process_name", "ph": "M", "pid": pid,
                     "args": {"name": node}})
    for (node, thread), tid in sorted(tids.items(),
                                      key=lambda kv: (pids[kv[0][0]], kv[1])):
        meta.append({"name": "thread_name", "ph": "M", "pid": pids[node],
                     "tid": tid, "args": {"name": thread}})
    return {
        "traceEvents": meta + events,
        "displayTimeUnit": "ms",
        "otherData": {
            "merged_nodes": sorted(pids, key=pids.get),
            "cross_node_edges": flow_id,
        },
    }


def merge_files(paths: list[str | Path]) -> dict[str, Any]:
    docs = [json.loads(Path(p).read_text()) for p in paths]
    names = [Path(p).stem for p in paths]
    return merge(docs, node_names=names)


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("dumps", nargs="+",
                    help="span-dump JSON files (obs.trace.export_spans)")
    ap.add_argument("--out", default="merged_trace.json",
                    help="merged chrome://tracing output path")
    args = ap.parse_args(argv)
    doc = merge_files(args.dumps)
    Path(args.out).write_text(json.dumps(doc))
    other = doc["otherData"]
    print(f"merged {len(args.dumps)} dump(s): {len(other['merged_nodes'])} "
          f"node lane(s) ({', '.join(other['merged_nodes'])}), "
          f"{other['cross_node_edges']} cross-node edge(s) -> {args.out}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
