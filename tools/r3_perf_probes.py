"""Round-3 targeted TPU perf probes (run alone on the chip).

Measures, honestly synced (utils.benchmarking), the four perf questions
VERDICT r2 left open:

  mldsa_sign_compact   ML-DSA-65 sign at batch 8192: the all-lanes loop vs
                       the compact-and-refill driver (next-round item #5)
  frodo_aes            FrodoKEM-640-AES encaps: bitsliced AES vs the gather
                       S-box (A/B needs fresh processes — this probe runs
                       whichever QRP2P_AES_GATHER selects; item #6)
  hqc_tpu              HQC-128 keygen/encaps/decaps at the safe batch cap
                       (the family's first TPU numbers; item #3)
  sphincs_s_sign       SPHINCS+-SHA2 s-set sign at increasing batches until
                       compile/run fails — locates the 128-lane ceiling
                       (item #8)

Usage:
    python -m tools.r3_perf_probes [--only NAME ...] [--out PATH]
    QRP2P_AES_GATHER=1 python -m tools.r3_perf_probes --only frodo_aes
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

import numpy as np  # noqa: E402

from quantum_resistant_p2p_tpu.utils.benchmarking import (  # noqa: E402
    enable_compile_cache,
    sync,
    timeit,
)


def _u8(shape) -> np.ndarray:
    rng = np.random.default_rng(20260730)
    return rng.integers(0, 256, shape, dtype=np.uint8)


def probe_mldsa_sign_compact(out: dict) -> None:
    import jax

    from quantum_resistant_p2p_tpu.sig import mldsa

    batch = 8192
    kg, sign_mu, _ = mldsa.get("ML-DSA-65")
    xi = _u8((batch, 32))
    _, sk = kg(xi)
    sync(sk)
    sk = jax.device_put(np.asarray(sk))
    mus = jax.device_put(_u8((batch, 64)))
    rnds = jax.device_put(_u8((batch, 32)))

    def compact():
        sig, done = mldsa.sign_mu_compact("ML-DSA-65", sk, mus, rnds)
        assert done.all()
        return sig

    # compact driver includes its own host orchestration; time wall-clock
    import time as _t

    compact()  # compile all bucket variants
    t0 = _t.perf_counter()
    compact()
    dt_c = _t.perf_counter() - t0
    t0 = _t.perf_counter()
    compact()
    dt_c = min(dt_c, _t.perf_counter() - t0)

    dt_full = timeit(sign_mu, sk, mus, rnds)
    out["mldsa_sign_compact"] = {
        "batch": batch,
        "full_loop_sign_per_s": round(batch / dt_full, 1),
        "compact_sign_per_s": round(batch / dt_c, 1),
        "speedup": round(dt_full / dt_c, 2),
    }


def probe_frodo_aes(out: dict) -> None:
    import os

    import jax

    from quantum_resistant_p2p_tpu.kem import frodo

    batch = frodo.MAX_DEVICE_BATCH
    kg, enc, _ = frodo.get("FrodoKEM-640-AES")
    sec = 16
    s1, s2, s3 = (_u8((batch, sec)) for _ in range(3))
    pk, sk = kg(s1, s2, s3)
    sync((pk, sk))
    pk = jax.device_put(np.asarray(pk))
    mu = jax.device_put(_u8((batch, sec)))
    dt = timeit(enc, pk, mu)
    out["frodo_aes"] = {
        "batch": batch,
        "aes_impl": "gather" if os.environ.get("QRP2P_AES_GATHER") == "1"
        else "bitsliced",
        "encaps_per_s": round(batch / dt, 1),
    }


def probe_hqc_tpu(out: dict) -> None:
    import jax

    from quantum_resistant_p2p_tpu.kem import hqc

    batch = hqc.MAX_DEVICE_BATCH
    kg, enc, dec = hqc.get("HQC-128")
    from quantum_resistant_p2p_tpu.pyref.hqc_ref import PARAMS

    p = PARAMS["HQC-128"]
    sk_seed, sigma, pk_seed = (
        _u8((batch, 40)), _u8((batch, p.k)), _u8((batch, 40))
    )
    pk, sk = kg(sk_seed, sigma, pk_seed)
    sync((pk, sk))
    pk_d, sk_d = jax.device_put(np.asarray(pk)), jax.device_put(np.asarray(sk))
    m, salt = jax.device_put(_u8((batch, p.k))), jax.device_put(_u8((batch, 16)))
    ct, ss = enc(pk_d, m, salt)
    sync((ct, ss))
    ct_d = jax.device_put(np.asarray(ct))
    ss2 = dec(sk_d, ct_d)
    assert np.array_equal(np.asarray(ss2), np.asarray(ss)), "roundtrip"
    out["hqc_tpu"] = {
        "batch": batch,
        "cyclic_impl": hqc._cyclic_impl(),
        "keygen_per_s": round(batch / timeit(kg, sk_seed, sigma, pk_seed), 1),
        "encaps_per_s": round(batch / timeit(enc, pk_d, m, salt), 1),
        "decaps_per_s": round(batch / timeit(dec, sk_d, ct_d), 1),
    }


def probe_sphincs_s_sign(out: dict) -> None:
    import jax

    from quantum_resistant_p2p_tpu.pyref import slhdsa_ref
    from quantum_resistant_p2p_tpu.sig import sphincs

    res = {}
    for name, batches in (
        # layered sign (sphincs.sign_digest_layered, the s-set default since
        # round 3) compiles one XMSS-layer program instead of the whole
        # hypertree; the ladders probe past the monolithic ceilings
        # (128 / 64 / fails-at-32 respectively).  Measured so far: 256s
        # fails-at-32 -> 16/s at 32; 128s 128 -> 512; 192s ceiling unmoved.
        ("SPHINCS+-SHA2-128s-simple", (128, 256, 512, 1024)),
        ("SPHINCS+-SHA2-192s-simple", (64, 128, 256, 512)),
        ("SPHINCS+-SHA2-256s-simple", (32, 64, 128, 256)),
    ):
        p = slhdsa_ref.PARAMS[name]
        _, ssign, _ = sphincs.get(name)
        # keys via ONE native-CPU keygen, repeated across the batch: keeps
        # the device keygen compile (a monolithic 2^hp-leaf tree build)
        # out of the probe so a failed rung locates the SIGN ceiling
        from quantum_resistant_p2p_tpu.provider import get_signature

        _, sk_one = get_signature(name, backend="cpu").generate_keypair()
        per_batch = {}
        for b in batches:
            # remote-compile-helper 500s are often TRANSIENT (same class as
            # the round-2 "worker fault"); retry a failed rung once so only
            # twice-failed rungs count as the ceiling
            for attempt in (1, 2):
                try:
                    sk = np.tile(np.frombuffer(sk_one, np.uint8), (b, 1))
                    sk_d = jax.device_put(sk)
                    r, digest = (
                        jax.device_put(_u8((b, p.n))),
                        jax.device_put(_u8((b, p.m))),
                    )
                    dt = timeit(ssign, sk_d, r, digest)
                    per_batch[str(b)] = round(b / dt, 2)
                    break
                except Exception as e:  # OOM / compile failure
                    per_batch[str(b)] = (
                        f"FAILED x{attempt}: {type(e).__name__}: {str(e)[:160]}"
                    )
            if not isinstance(per_batch[str(b)], (int, float)):
                break  # twice-failed rung locates the ceiling
        res[name] = per_batch
    out["sphincs_s_sign"] = res


def probe_mlkem_breakdown(out: dict) -> None:
    """Per-stage timing of ML-KEM-768 encaps at the provider's slice size
    (1024): locates where the next headline point lives.  Parts are timed
    as standalone jitted programs (device-resident operands), so their sum
    exceeds the fused whole — the ranking, not the absolute split, is the
    signal."""
    import jax
    import jax.numpy as jnp

    from quantum_resistant_p2p_tpu.kem import mlkem

    batch = 1024
    p = mlkem.PARAMS["ML-KEM-768"]
    k = p.k
    rng = np.random.default_rng(20260731)
    rho = jax.device_put(rng.integers(0, 256, (batch, 32), dtype=np.uint8))
    r32 = jax.device_put(rng.integers(0, 256, (batch, 32), dtype=np.uint8))
    polys = jax.device_put(
        rng.integers(0, mlkem.Q, (batch, k, mlkem.N), dtype=np.int32)
    )
    mat = jax.device_put(
        rng.integers(0, mlkem.Q, (batch, k, k, mlkem.N), dtype=np.int32)
    )
    ek, _ = mlkem.get("ML-KEM-768")[0](
        jax.device_put(rng.integers(0, 256, (batch, 32), dtype=np.uint8)),
        jax.device_put(rng.integers(0, 256, (batch, 32), dtype=np.uint8)),
    )
    sync(ek)
    ek = jax.device_put(np.asarray(ek))
    m = jax.device_put(rng.integers(0, 256, (batch, 32), dtype=np.uint8))

    jj = jax.jit
    parts = {
        "expand_matrix": (jj(lambda r: mlkem._expand_matrix(r, k)), (rho,)),
        "prf_cbd_eta2_x3": (
            jj(lambda s: mlkem._prf_cbd(s, np.arange(k), 2)), (r32,)),
        "ntt_3polys": (jj(mlkem.ntt), (polys,)),
        "ntt_inv_3polys": (jj(mlkem.ntt_inv), (polys,)),
        "matvec_basemul": (
            jj(lambda a, y: jnp.sum(
                mlkem.multiply_ntts(a, y[..., :, None, :]), axis=-3) % mlkem.Q),
            (mat, polys)),
        "byte_encode_d12": (jj(lambda x: mlkem.byte_encode(x, 12)), (polys,)),
        "byte_decode_d12": (
            jj(lambda b: mlkem.byte_decode(
                b.reshape(b.shape[:-1] + (k, 384)), 12)),
            (jax.device_put(
                rng.integers(0, 256, (batch, 384 * k), dtype=np.uint8)),)),
        "compress_encode_du10": (
            jj(lambda x: mlkem.byte_encode(mlkem.compress(x, 10), 10)), (polys,)),
        "full_encaps": (mlkem.get("ML-KEM-768")[1], (ek, m)),
    }
    res = {}
    for name, (fn, args) in parts.items():
        dt = timeit(fn, *args)
        res[name] = {"ms_per_1024": round(dt * 1e3, 3),
                     "ops_per_s": round(batch / dt, 1)}
    out["mlkem_breakdown"] = res


PROBES = {
    "mldsa_sign_compact": probe_mldsa_sign_compact,
    "frodo_aes": probe_frodo_aes,
    "hqc_tpu": probe_hqc_tpu,
    "sphincs_s_sign": probe_sphincs_s_sign,
    "mlkem_breakdown": probe_mlkem_breakdown,
}


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--only", nargs="*")
    ap.add_argument("--out", default="bench_results/r3_perf_probes.json")
    args = ap.parse_args(argv)
    enable_compile_cache()
    import jax

    out: dict = {"platform": jax.devices()[0].platform}
    path = Path(args.out)
    path.parent.mkdir(parents=True, exist_ok=True)
    for name in (args.only or list(PROBES)):
        print(f"== {name}", flush=True)
        try:
            PROBES[name](out)
        except Exception as e:
            out[name] = f"ERROR: {type(e).__name__}: {str(e)[:300]}"
        print(json.dumps(out.get(name), indent=1), flush=True)
        path.write_text(json.dumps(out, indent=1))
    return 0


if __name__ == "__main__":
    sys.exit(main())
