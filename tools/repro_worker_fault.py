"""Minimized repro / bisection harness for the remote-TPU worker kernel fault.

Round-2 observations (commit f28a9b0, memory notes): FrodoKEM single
dispatches >= 1024 rows and HQC >= 256 rows reproducibly crash this
environment's remote TPU worker (it restarts after ~1 min); the fix was
MAX_DEVICE_BATCH caps chosen by observation.  This tool turns that
observation into a bisection: it runs each candidate sub-kernel at
increasing batch sizes, EACH IN ITS OWN SUBPROCESS (a worker crash kills
the child, not the harness), verifies chip health with a tiny program
between runs, and emits a JSON map  probe -> largest-ok / smallest-fault
batch, so the fault can be attributed to a specific kernel (HQC's cyclic
gather chain vs its RS/RM decoders vs the seedexpander; Frodo's SHAKE
matrix-gen vs the MXU matmul) rather than to "the op".

Respect the one-TPU-process rule: run this alone.

Usage:
    python -m tools.repro_worker_fault                    # full bisection
    python -m tools.repro_worker_fault --probe hqc_keygen --batch 256
                                                          # one child probe
"""

from __future__ import annotations

import argparse
import json
import subprocess
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

PROBE_TIMEOUT_S = 600  # first compile of a big batch is slow on the tunnel
HEALTH_TIMEOUT_S = 120
RESTART_WAIT_S = 75  # worker restart takes ~1 min


# --------------------------------------------------------------------------
# Child-side probes: each builds ONE kernel at the given batch and runs it.
# Data is random; decode probes run on garbage inputs (fault-probing only).
# --------------------------------------------------------------------------


def _rng_u8(rng, *shape):
    import numpy as np

    return rng.integers(0, 256, shape, dtype=np.uint8)


def probe_tiny(batch: int) -> None:
    import jax.numpy as jnp

    assert int((jnp.ones((8,)) * 2).sum()) == 16


def _hqc_parts(batch):
    import numpy as np

    from quantum_resistant_p2p_tpu.pyref.hqc_ref import PARAMS

    p = PARAMS["HQC-128"]
    rng = np.random.default_rng(0)
    return p, rng


def probe_hqc_seedexpand(batch: int) -> None:
    import jax

    from quantum_resistant_p2p_tpu.kem import hqc

    p, rng = _hqc_parts(batch)
    import numpy as np

    out = jax.jit(lambda s: hqc._seedexpand(s, 8 * p.w))(_rng_u8(rng, batch, 40))
    _ = bytes(np.asarray(out)[0, :4])  # host readback


def probe_hqc_fixed_weight(batch: int) -> None:
    import jax

    from quantum_resistant_p2p_tpu.kem import hqc

    p, rng = _hqc_parts(batch)

    def f(seed):
        stream = hqc._u32s(hqc._seedexpand(seed, 8 * p.w))
        return hqc._fixed_weight_support(p, stream[..., : p.w], p.w)

    out = jax.jit(f)(_rng_u8(rng, batch, 40))
    _ = int(jax.numpy.asarray(out)[0, 0])


def probe_hqc_cyclic_mul(batch: int) -> None:
    import jax
    import jax.numpy as jnp
    import numpy as np

    from quantum_resistant_p2p_tpu.kem import hqc

    p, rng = _hqc_parts(batch)
    dense = jnp.asarray(rng.integers(0, 2, (batch, p.n), dtype=np.int32))
    sup = jnp.asarray(rng.integers(0, p.n, (batch, p.w), dtype=np.int32))
    out = jax.jit(lambda d, s: hqc._cyclic_mul_sparse(p, d, s))(dense, sup)
    _ = int(jax.numpy.asarray(out)[0, 0])


def probe_hqc_rm_rs_decode(batch: int) -> None:
    import jax
    import jax.numpy as jnp
    import numpy as np

    from quantum_resistant_p2p_tpu.kem import hqc

    p, rng = _hqc_parts(batch)
    bits = jnp.asarray(rng.integers(0, 2, (batch, p.n1 * p.n2), dtype=np.int32))
    out = jax.jit(lambda b: hqc._rs_decode(p, hqc._rm_decode(p, b)))(bits)
    _ = int(jax.numpy.asarray(out)[0, 0])


def _hqc_full(op: str, batch: int) -> None:
    import jax
    import numpy as np

    from quantum_resistant_p2p_tpu.kem import hqc

    p, rng = _hqc_parts(batch)
    kg, enc, dec = hqc.get("HQC-128")
    sk_seed, sigma, pk_seed = (
        _rng_u8(rng, batch, 40), _rng_u8(rng, batch, p.k), _rng_u8(rng, batch, 40)
    )
    if op == "keygen":
        pk, sk = kg(sk_seed, sigma, pk_seed)
        _ = bytes(np.asarray(pk)[0, :4])
        return
    # encaps/decaps need keys: make them at a SAFE batch then broadcast
    pk1, sk1 = kg(sk_seed[:1], sigma[:1], pk_seed[:1])
    pk = np.broadcast_to(np.asarray(pk1), (batch, pk1.shape[-1]))
    if op == "encaps":
        ct, ss = enc(pk, _rng_u8(rng, batch, p.k), _rng_u8(rng, batch, 16))
        _ = bytes(np.asarray(ss)[0, :4])
        return
    ct1, _ = enc(np.asarray(pk1), _rng_u8(rng, 1, p.k), _rng_u8(rng, 1, 16))
    sk = np.broadcast_to(np.asarray(sk1), (batch, sk1.shape[-1]))
    ct = np.broadcast_to(np.asarray(ct1), (batch, ct1.shape[-1]))
    ss = dec(sk, ct)
    _ = bytes(np.asarray(ss)[0, :4])


def probe_hqc_keygen(batch: int) -> None:
    _hqc_full("keygen", batch)


def probe_hqc_encaps(batch: int) -> None:
    _hqc_full("encaps", batch)


def probe_hqc_decaps(batch: int) -> None:
    _hqc_full("decaps", batch)


def _frodo_parts():
    import numpy as np

    from quantum_resistant_p2p_tpu.pyref.frodo_ref import PARAMS

    return PARAMS["FrodoKEM-640-SHAKE"], np.random.default_rng(1)


def probe_frodo_gen_a(batch: int) -> None:
    """The SHAKE row-expansion of A alone (no matmul)."""
    import jax

    from quantum_resistant_p2p_tpu.kem import frodo

    p, rng = _frodo_parts()

    def f(seed_a):
        ctx = frodo._a_ctx(p, seed_a)
        return frodo._gen_a_chunk(p, ctx, 0, 64)

    out = jax.jit(f)(_rng_u8(rng, batch, 16))
    _ = int(jax.numpy.asarray(out)[0, 0, 0])


def probe_frodo_matmul(batch: int) -> None:
    """A x S einsum chain alone (MXU path) at full n=640."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from quantum_resistant_p2p_tpu.kem import frodo

    p, rng = _frodo_parts()
    seed_a = _rng_u8(rng, batch, 16)
    s = jnp.asarray(
        rng.integers(0, p.q, (batch, p.n, 8), dtype=np.int32)
    )

    def f(seed_a, s):
        ctx = frodo._a_ctx(p, seed_a)
        return frodo._a_times_s(p, ctx, s)

    out = jax.jit(f)(seed_a, s)
    _ = int(jax.numpy.asarray(out)[0, 0, 0])


def _frodo_full(op: str, batch: int) -> None:
    import numpy as np

    from quantum_resistant_p2p_tpu.kem import frodo

    p, rng = _frodo_parts()
    kg, enc, dec = frodo.get("FrodoKEM-640-SHAKE")
    sec = p.len_sec
    if op == "keygen":
        pk, sk = kg(_rng_u8(rng, batch, sec), _rng_u8(rng, batch, sec),
                    _rng_u8(rng, batch, sec))
        _ = bytes(np.asarray(pk)[0, :4])
        return
    pk1, sk1 = kg(_rng_u8(rng, 1, sec), _rng_u8(rng, 1, sec), _rng_u8(rng, 1, sec))
    pk = np.broadcast_to(np.asarray(pk1), (batch, pk1.shape[-1]))
    if op == "encaps":
        ct, ss = enc(pk, _rng_u8(rng, batch, sec))
        _ = bytes(np.asarray(ss)[0, :4])
        return
    ct1, _ = enc(np.asarray(pk1), _rng_u8(rng, 1, sec))
    sk = np.broadcast_to(np.asarray(sk1), (batch, sk1.shape[-1]))
    ct = np.broadcast_to(np.asarray(ct1), (batch, ct1.shape[-1]))
    ss = dec(sk, ct)
    _ = bytes(np.asarray(ss)[0, :4])


def probe_frodo_keygen(batch: int) -> None:
    _frodo_full("keygen", batch)


def probe_frodo_encaps(batch: int) -> None:
    _frodo_full("encaps", batch)


def probe_frodo_decaps(batch: int) -> None:
    _frodo_full("decaps", batch)


PROBES = {
    "tiny": (probe_tiny, [1]),
    # HQC sub-kernels, bracketing the observed >=256 fault threshold
    "hqc_seedexpand": (probe_hqc_seedexpand, [128, 256, 512, 1024]),
    "hqc_fixed_weight": (probe_hqc_fixed_weight, [128, 256, 512, 1024]),
    "hqc_cyclic_mul": (probe_hqc_cyclic_mul, [128, 256, 512, 1024]),
    "hqc_rm_rs_decode": (probe_hqc_rm_rs_decode, [128, 256, 512, 1024]),
    "hqc_keygen": (probe_hqc_keygen, [128, 192, 256, 512]),
    "hqc_encaps": (probe_hqc_encaps, [128, 192, 256, 512]),
    "hqc_decaps": (probe_hqc_decaps, [128, 192, 256]),
    # Frodo sub-kernels, bracketing the observed >=1024 fault threshold
    "frodo_gen_a": (probe_frodo_gen_a, [256, 512, 1024, 2048]),
    "frodo_matmul": (probe_frodo_matmul, [256, 512, 1024, 2048]),
    "frodo_keygen": (probe_frodo_keygen, [256, 512, 768, 1024]),
    "frodo_encaps": (probe_frodo_encaps, [256, 512, 768, 1024]),
    "frodo_decaps": (probe_frodo_decaps, [256, 512, 1024]),
}


def _run_child(probe: str, batch: int, timeout: float) -> dict:
    t0 = time.time()
    try:
        r = subprocess.run(
            [sys.executable, "-m", "tools.repro_worker_fault",
             "--probe", probe, "--batch", str(batch)],
            capture_output=True, text=True, timeout=timeout,
            cwd=Path(__file__).resolve().parent.parent,
        )
        status = "ok" if r.returncode == 0 else "fault"
        detail = (r.stderr or "")[-400:] if r.returncode else ""
    except subprocess.TimeoutExpired:
        status, detail = "timeout", ""
    return {"status": status, "elapsed_s": round(time.time() - t0, 1),
            "detail": detail}


def _wait_healthy() -> bool:
    for attempt in range(6):
        if _run_child("tiny", 1, HEALTH_TIMEOUT_S)["status"] == "ok":
            return True
        print(f"  chip unhealthy; waiting {RESTART_WAIT_S}s for worker restart "
              f"(attempt {attempt + 1})", flush=True)
        time.sleep(RESTART_WAIT_S)
    return False


def bisect(probes: list[str], out_path: Path) -> dict:
    results: dict[str, dict] = {}
    for name in probes:
        _, batches = PROBES[name]
        results[name] = {}
        for batch in batches:
            print(f"{name} @ {batch} ...", end=" ", flush=True)
            res = _run_child(name, batch, PROBE_TIMEOUT_S)
            print(res["status"], f"({res['elapsed_s']}s)", flush=True)
            results[name][str(batch)] = res
            out_path.write_text(json.dumps(results, indent=1))
            if res["status"] != "ok":
                if not _wait_healthy():
                    print("chip did not recover; aborting", flush=True)
                    return results
                break  # larger batches of a faulting kernel: no new info
    return results


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--probe", help="child mode: run one probe and exit")
    ap.add_argument("--batch", type=int, default=128)
    ap.add_argument("--only", nargs="*", help="subset of probes to run")
    ap.add_argument("--out", default=None,
                    help="output JSON (default: bench_results/"
                         "worker_fault_bisect.json, or _atcap with --at-cap)")
    ap.add_argument(
        "--at-cap", action="store_true",
        help="sentinel mode: run each end-to-end probe ONCE at the family's "
             "current MAX_DEVICE_BATCH (the ADVICE round-3 ask: keep the "
             "repro in periodic runs after the 512 cap raise so a transient-"
             "fault recurrence is caught by tooling, not production fallback)")
    args = ap.parse_args(argv)

    if args.probe:
        fn, _ = PROBES[args.probe]
        fn(args.batch)
        print("ok")
        return 0

    out_path = Path(args.out or (
        "bench_results/worker_fault_atcap.json" if args.at_cap
        else "bench_results/worker_fault_bisect.json"))
    out_path.parent.mkdir(parents=True, exist_ok=True)

    if args.at_cap:
        from quantum_resistant_p2p_tpu.kem import frodo as _frodo, hqc as _hqc

        caps = {
            "hqc_keygen": _hqc.MAX_DEVICE_BATCH,
            "hqc_encaps": _hqc.MAX_DEVICE_BATCH,
            "hqc_decaps": _hqc.MAX_DEVICE_BATCH,
            "frodo_keygen": _frodo.MAX_DEVICE_BATCH,
            "frodo_encaps": _frodo.MAX_DEVICE_BATCH,
            "frodo_decaps": _frodo.MAX_DEVICE_BATCH,
        }
        if args.only:
            unknown = [name for name in args.only if name not in caps]
            if unknown:
                ap.error(f"--at-cap probes are {sorted(caps)}; unknown: {unknown}")
            caps = {k: v for k, v in caps.items() if k in args.only}
        if not _wait_healthy():
            print("chip not healthy at start", flush=True)
            return 1
        results = {}
        for name, cap in caps.items():
            print(f"{name} @ cap {cap} ...", end=" ", flush=True)
            res = _run_child(name, cap, PROBE_TIMEOUT_S)
            print(res["status"], f"({res['elapsed_s']}s)", flush=True)
            results[name] = {str(cap): res}
            out_path.write_text(json.dumps(results, indent=1))
            if res["status"] != "ok" and not _wait_healthy():
                print("chip did not recover; aborting", flush=True)
                break
        print(json.dumps(results, indent=1))
        return 0 if all(
            list(r.values())[0]["status"] == "ok" for r in results.values()
        ) else 1

    probes = args.only or [p for p in PROBES if p != "tiny"]
    if not _wait_healthy():
        print("chip not healthy at start", flush=True)
        return 1
    results = bisect(probes, out_path)
    print(json.dumps(results, indent=1))
    return 0


if __name__ == "__main__":
    sys.exit(main())
