"""Full BASELINE.json benchmark suite (all 5 configs) on the real device.

Configs (BASELINE.json):
  1. ML-KEM-768 single keygen+encaps+decaps — scalar CPU path (native C++,
     the role liboqs plays for the reference's crypto_algorithms_tester.py).
  2. ML-KEM-512/768/1024 batch=4096 keygen/encaps/decaps on the TPU backend,
     plus a batch-scaling curve for ML-KEM-768 encaps (256 -> 16384).
  3. FrodoKEM-640-AES batch=1024 on TPU (dense-LWE MXU matmul showcase).
  4. ML-DSA-65 batch=8192 sign + verify; SPHINCS+-SHA2-128s and 128f verify.
  5. 1000-peer swarm: real TCP handshakes through the batching queue
     (tools/swarm_bench.py).

Every timed region uses utils.benchmarking.timeit (forced host readback —
see that module for why block_until_ready is not sufficient on this
platform).  Results append incrementally to --out as JSON so a partial run
still leaves numbers behind.  An audit section records XLA cost analysis
(flops / bytes accessed) for the headline program so the numbers can be
checked against a roofline, and a sanity check proves ciphertexts depend on
the message input (nothing constant-folded).

Input residency: large operands (public keys, secret keys, ciphertexts) are
``jax.device_put`` BEFORE timing, so configs 2-4 measure device compute
throughput — the same methodology as liboqs's in-memory speed tests, and
what "ops/sec/chip" means.  This environment reaches its one chip through a
MB/s-scale tunnel (measured 0.4-2.2 MB/s across sessions, audit_tunnel),
so leaving multi-MB operands on
the host would time the tunnel, not the chip (measured: encaps drops
110k -> 6.4k/s, and decaps lands at exactly half encaps because dk is twice
the bytes).  The tunnel
h2d bandwidth is recorded separately in the audit section; config 5 (swarm)
times the complete production pipeline including every host<->device hop.

Usage: python -m tools.full_bench [--configs 1 2 3 4 5] [--out PATH]
"""

from __future__ import annotations

import argparse
import json
import sys
import time
from pathlib import Path

import numpy as np

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

BASELINE_ENCAPS_PER_S = 50_000.0  # north-star target (BASELINE.md)
REFERENCE_HANDSHAKE_S = 0.25     # reference's measured ML-KEM+ML-DSA handshake

RNG = np.random.default_rng(20260730)


def _u8(shape) -> np.ndarray:
    return RNG.integers(0, 256, size=shape, dtype=np.uint8)


def jnp_tile(arr, reps: int):
    """Tile a device array along axis 0 (stays on device)."""
    import jax.numpy as jnp

    return jnp.tile(jnp.asarray(arr), (reps,) + (1,) * (arr.ndim - 1))


def _result(out: dict, section: str, payload: dict, path: Path) -> None:
    # per-section stamp: the resume-merge (main) can combine runs from
    # different days, so provenance lives with each section, not the file
    out.setdefault(section, {}).update(payload)
    out[section]["measured_at"] = time.strftime("%Y%m%d_%H%M%S")
    path.write_text(json.dumps(out, indent=2))
    print(f"[{section}] {json.dumps(payload)}", flush=True)


# -- config 1: scalar CPU path ------------------------------------------------

def bench_config1(out: dict, path: Path) -> None:
    from quantum_resistant_p2p_tpu.provider import get_kem, get_signature

    kem = get_kem("ML-KEM-768", "cpu")
    res = {"impl": kem.description}
    pk, sk = kem.generate_keypair()
    ct, ss = kem.encapsulate(pk)

    def rate(fn, n=200) -> float:
        t0 = time.perf_counter()
        for _ in range(n):
            fn()
        return n / (time.perf_counter() - t0)

    res["keygen_per_s"] = round(rate(kem.generate_keypair), 1)
    res["encaps_per_s"] = round(rate(lambda: kem.encapsulate(pk)), 1)
    res["decaps_per_s"] = round(rate(lambda: kem.decapsulate(sk, ct)), 1)

    sig = get_signature("ML-DSA-65", "cpu")
    spk, ssk = sig.generate_keypair()
    s = sig.sign(ssk, b"bench")
    res["mldsa65_sign_per_s"] = round(rate(lambda: sig.sign(ssk, b"bench"), 100), 1)
    res["mldsa65_verify_per_s"] = round(rate(lambda: sig.verify(spk, b"bench", s), 100), 1)
    _result(out, "config1_scalar_cpu", res, path)


# -- config 2: batched ML-KEM on TPU -----------------------------------------

def bench_config2(out: dict, path: Path) -> None:
    import jax

    from quantum_resistant_p2p_tpu.kem import mlkem
    from quantum_resistant_p2p_tpu.utils.benchmarking import sync, timeit

    # tunnel h2d bandwidth audit: how fast CAN operands reach the chip here
    blob = _u8((4096, 1184))
    t0 = time.perf_counter()
    sync(jax.device_put(blob))
    h2d_s = time.perf_counter() - t0
    _result(out, "audit_tunnel", {
        "h2d_mb_per_s": round(blob.nbytes / 1e6 / h2d_s, 1),
        "note": "remote-TPU tunnel; configs 2-4 time device compute with "
                "device-resident operands (see module docstring)",
    }, path)

    batch = 4096
    for name in ("ML-KEM-512", "ML-KEM-768", "ML-KEM-1024"):
        kg, enc, dec = mlkem.get(name)
        # device-resident operands per the module docstring (ek/dk/ct are
        # device outputs already; the seeds/messages must be device_put or
        # every timed call re-sends them through the tunnel)
        d, z, m = (jax.device_put(_u8((batch, 32))) for _ in range(3))
        ek, dk = kg(d, z)
        sync((ek, dk))
        key, ct = enc(ek, m)
        sync((key, ct))
        res = {
            "batch": batch,
            "keygen_per_s": round(batch / timeit(kg, d, z), 1),
            "encaps_per_s": round(batch / timeit(enc, ek, m), 1),
            "decaps_per_s": round(batch / timeit(dec, dk, ct), 1),
        }
        if name == "ML-KEM-768":
            res["vs_baseline_encaps"] = round(res["encaps_per_s"] / BASELINE_ENCAPS_PER_S, 3)
            # audit: XLA cost analysis of the compiled encaps program
            try:
                lowered = jax.jit(lambda e, mm: mlkem.get(name)[1](e, mm)).lower(
                    np.asarray(ek), m
                )
                ca = lowered.compile().cost_analysis()
                if isinstance(ca, (list, tuple)):
                    ca = ca[0]
                res["xla_cost_analysis"] = {
                    k: ca[k] for k in ("flops", "bytes accessed") if k in ca
                }
            except Exception as e:  # cost analysis is best-effort per backend
                res["xla_cost_analysis"] = f"unavailable: {e}"
            # sanity: ciphertext depends on m (nothing folded to a constant)
            m2 = np.asarray(m).copy()
            m2[0, 0] ^= 1
            _, ct2 = enc(ek, m2)
            res["ct_depends_on_m"] = bool(
                (np.asarray(ct)[0] != np.asarray(ct2)[0]).any()
                and (np.asarray(ct)[1] == np.asarray(ct2)[1]).all()
            )
        _result(out, f"config2_{name}", res, path)

    # batch-scaling curve for the headline op
    kg, enc, _ = mlkem.get("ML-KEM-768")
    curve = {}
    for b in (256, 512, 1024, 2048, 4096, 8192, 16384):
        d, z, m = (jax.device_put(_u8((b, 32))) for _ in range(3))
        ek, _dk = kg(d, z)
        sync(ek)
        curve[str(b)] = round(b / timeit(enc, ek, m), 1)
    _result(out, "config2_scaling_mlkem768_encaps", curve, path)


# -- config 3: FrodoKEM on TPU ------------------------------------------------

def bench_config3(out: dict, path: Path) -> None:
    from quantum_resistant_p2p_tpu.kem import frodo
    from quantum_resistant_p2p_tpu.pyref import frodo_ref
    from quantum_resistant_p2p_tpu.utils.benchmarking import sync, timeit

    p = frodo_ref.FRODO640AES
    batch = 1024
    # Single dispatches >= 1024 reproducibly crash this environment's TPU
    # worker (kem/frodo.py MAX_DEVICE_BATCH); the 1024 batch runs as
    # back-to-back sliced dispatches, exactly as the provider does.
    step = frodo.MAX_DEVICE_BATCH
    reps = batch // step
    kg, enc, dec = frodo.get(p.name)
    s1, s2, s3 = _u8((step, p.len_sec)), _u8((step, p.len_sec)), _u8((step, p.len_sec))
    pk, sk = kg(s1, s2, s3)
    sync((pk, sk))
    mu = _u8((step, p.len_sec))
    ct, ss = enc(pk, mu)
    sync((ct, ss))

    def n_of(fn, *a):
        def run():
            o = None
            for _ in range(reps):
                o = fn(*a)
            return o

        return run

    _result(
        out,
        "config3_frodo640aes",
        {
            "batch": batch,
            "dispatch_slice": step,
            "keygen_per_s": round(batch / timeit(n_of(kg, s1, s2, s3)), 1),
            "encaps_per_s": round(batch / timeit(n_of(enc, pk, mu)), 1),
            "decaps_per_s": round(batch / timeit(n_of(dec, sk, ct)), 1),
        },
        path,
    )


# -- config 4: signatures on TPU ---------------------------------------------

def bench_config4(out: dict, path: Path) -> None:
    from quantum_resistant_p2p_tpu.sig import mldsa, sphincs
    from quantum_resistant_p2p_tpu.pyref import slhdsa_ref
    from quantum_resistant_p2p_tpu.utils.benchmarking import sync, timeit

    batch = 8192
    kg, sign_mu, verify_mu = mldsa.get("ML-DSA-65")
    xi = _u8((batch, 32))
    pk, sk = kg(xi)
    sync((pk, sk))
    mus, rnds = _u8((batch, 64)), _u8((batch, 32))
    sigs, done = sign_mu(sk, mus, rnds)
    sync((sigs, done))
    assert bool(np.asarray(done).all())
    _result(
        out,
        "config4_mldsa65",
        {
            "batch": batch,
            "keygen_per_s": round(batch / timeit(kg, xi), 1),
            "sign_per_s": round(batch / timeit(sign_mu, sk, mus, rnds), 1),
            "verify_per_s": round(batch / timeit(verify_mu, pk, mus, sigs), 1),
        },
        path,
    )

    # config 4 names 128s VERIFY; sign batches are kept small for the 's'
    # sets (FORS holds k * 2^a leaves in HBM during signing).
    for name, vbatch, sbatch in (
        ("SPHINCS+-SHA2-128s-simple", 2048, 128),
        ("SPHINCS+-SHA2-128f-simple", 2048, 1024),
    ):
        p = slhdsa_ref.PARAMS[name]
        skg, ssign, sverify = sphincs.get(name)
        n = p.n
        sk_seed, sk_prf, pk_seed = _u8((sbatch, n)), _u8((sbatch, n)), _u8((sbatch, n))
        spk, ssk = skg(sk_seed, sk_prf, pk_seed)
        sync((spk, ssk))
        r, digest = _u8((sbatch, n)), _u8((sbatch, p.m))
        sigs = ssign(ssk, r, digest)
        sync(sigs)
        reps = vbatch // sbatch
        vpk = jnp_tile(spk, reps)
        vdig = jnp_tile(digest, reps)
        vsigs = jnp_tile(sigs, reps)
        ok = sverify(vpk, vdig, vsigs)
        assert bool(np.asarray(ok).all())
        _result(
            out,
            f"config4_{name}",
            {
                "verify_batch": vbatch,
                "verify_per_s": round(vbatch / timeit(sverify, vpk, vdig, vsigs), 1),
                "sign_batch": sbatch,
                "sign_per_s": round(sbatch / timeit(ssign, ssk, r, digest), 1),
            },
            path,
        )


# -- config 5: swarm ----------------------------------------------------------

def bench_config5(out: dict, path: Path, peers: int) -> None:
    import asyncio

    from tools.swarm_bench import run_swarm

    stats = asyncio.run(
        run_swarm(peers, backend="tpu", use_batching=True, max_batch=4096,
                  max_wait_ms=3.0, concurrency=256, warmup=32)
    )
    _result(out, "config5_swarm", stats, path)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--configs", nargs="*", type=int, default=[1, 2, 3, 4, 5])
    ap.add_argument("--peers", type=int, default=1000)
    ap.add_argument("--out", default=None)
    args = ap.parse_args(argv)

    stamp = time.strftime("%Y%m%d_%H%M%S")
    path = Path(args.out or f"bench_results/full_bench_{stamp}.json")
    path.parent.mkdir(parents=True, exist_ok=True)
    # resume-friendly: merge into an existing results file (re-run a single
    # crashed config without losing the rest)
    out: dict = {}
    if path.exists():
        try:
            out = json.loads(path.read_text())
        except json.JSONDecodeError:
            out = {}
    out["stamp"] = stamp
    try:
        import jax

        from quantum_resistant_p2p_tpu.utils.benchmarking import enable_compile_cache

        enable_compile_cache()
        out["platform"] = jax.default_backend()
        out["devices"] = [str(d) for d in jax.devices()]
    except Exception:
        pass
    path.write_text(json.dumps(out, indent=2))

    dispatch = {1: bench_config1, 2: bench_config2, 3: bench_config3,
                4: bench_config4,
                5: lambda o, p: bench_config5(o, p, args.peers)}
    unknown = [c for c in args.configs if c not in dispatch]
    if unknown:
        ap.error(f"unknown configs {unknown}; valid: 1-5")
    for cfg in args.configs:
        t0 = time.time()
        dispatch[cfg](out, path)
        print(f"config {cfg} done in {time.time() - t0:.1f}s", flush=True)
    print(json.dumps(out))
    return 0


if __name__ == "__main__":
    sys.exit(main())
