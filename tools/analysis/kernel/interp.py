"""qrkernel abstract interpreter over the JAX/Pallas kernel modules.

Pure AST + abstract domains (absdom.py) — **no jax import**: the analyzer
runs on minimal no-jax images, exactly like qrlint/qrflow.  One
:class:`Interp` is built per run; it loads kernel modules (resolving
relative imports to sibling files on disk, so ``from ..core.keccak_pallas
import block_bytes`` summarises across files), evaluates module constants
(``Q = 3329``, ``BT = _TS * _TL``, ``pow(_N, -1, Q)``), and abstractly
executes every function of every checked module:

* concrete loops (``range(24)``, concrete-length lists) are unrolled up to
  :data:`UNROLL_LIMIT` iterations — the same full unroll the real Pallas
  trace performs; everything else runs to a join fixpoint with widening;
* calls to project functions use context-insensitive memoized summaries
  (parameters seeded from ``# qrkernel: assume`` contracts when declared,
  TOP tiles otherwise), so a summary is sound for every call site;
* every ``*``/``<<`` whose operands are (derived from) kernel tiles is a
  **site**: the mathematical interval of the product is recorded and
  checked against the value's dtype (int32 when unknown — the TPU vreg
  width).  A site is *proved* when the math provably fits, *wrapping* when
  the line carries a ``# qrkernel: wrapping — justification`` annotation
  (Keccak rotations: bits shifted out by design), *unproven* otherwise.

Annotations (both policed for a justification by the rule pack):

``# qrkernel: assume NAME in [LO, HI) — justification``
    Declares a parameter contract for the enclosing function; LO/HI are
    expressions over module constants (``[0, Q)``).  The analyzer seeds the
    parameter from it AND checks every call site whose argument interval is
    known: an argument provably outside the contract is a
    ``kernel-contract-violation``.

``# qrkernel: wrapping — justification``
    Marks the ``*``/``<<`` sites on this line as wrap-by-design.
"""

from __future__ import annotations

import ast
import dataclasses
import re
from pathlib import Path
from typing import Any, Callable

from .absdom import (DEFAULT_CHECK_DTYPE, DTYPE_WIDTH, FLOAT_DTYPES,
                     INT_DTYPES, Dim, IVal,
                     add, bitand, bitor, bitxor, compare, dim_of, floordiv,
                     invert, join_all, lshift, mod, mul, neg, rshift, sub)

#: concrete loops at or under this trip count are unrolled; larger ones and
#: symbolic ones run to a join fixpoint instead
UNROLL_LIMIT = 256
#: abstract-evaluation steps per function before the analysis of that
#: function is abandoned (its summary degrades to TOP, its sites to unproven)
FUNC_BUDGET = 150_000
#: fixpoint passes before widening kicks in
FIX_PASSES = 3

_ASSUME_RE = re.compile(
    r"#\s*qrkernel:\s*assume\s+(?P<name>\w+)\s+in\s+"
    r"(?P<open>[\[(])\s*(?P<lo>[^,]+?)\s*,\s*(?P<hi>[^\])]+?)\s*(?P<close>[\])])"
    r"(?P<just>.*)$")
_WRAPPING_RE = re.compile(r"#\s*qrkernel:\s*wrapping(?P<just>.*)$")

#: function-name suffixes whose parameters are VMEM tiles (qrlint's scoping)
TILE_FUNC_SUFFIXES = ("_kernel", "_tiles")


# -- value classes beyond IVal ------------------------------------------------


class LVal:
    """Abstract list: concrete element vector, or a summarised (elem, len)."""

    __slots__ = ("elems", "elem", "length")

    def __init__(self, elems: list | None = None, elem: Any = None,
                 length: IVal | None = None):
        self.elems = elems
        self.elem = elem
        self.length = length if length is not None else (
            IVal.const(len(elems)) if elems is not None else IVal(0, None))

    @property
    def concrete(self) -> bool:
        return self.elems is not None

    def join_elem(self) -> Any:
        """Join of the elements — ``None`` is BOTTOM (an empty list has no
        elements, so it must be the identity of a join, never TOP: joining
        the `cand = []` entry state into a loop fixpoint must not destroy
        the element bounds of everything appended later)."""
        if self.concrete:
            if not self.elems:
                return None
            out = self.elems[0]
            for e in self.elems[1:]:
                out = _join_values(out, e)
            return out
        return self.elem

    def summarised(self) -> "LVal":
        if not self.concrete:
            return self
        return LVal(elem=self.join_elem(), length=IVal.const(len(self.elems)))


class TVal:
    __slots__ = ("elems",)

    def __init__(self, elems: tuple):
        self.elems = tuple(elems)


class FuncVal:
    """A project function (or lambda/closure), optionally with bound args."""

    __slots__ = ("node", "module", "closure", "bound_args", "bound_kwargs",
                 "jitted", "donate")

    def __init__(self, node, module, closure=None, bound_args=(),
                 bound_kwargs=None, jitted=False, donate=()):
        self.node = node
        self.module = module
        self.closure = closure
        self.bound_args = tuple(bound_args)
        self.bound_kwargs = dict(bound_kwargs or {})
        self.jitted = jitted
        self.donate = tuple(donate)


class ModRef:
    __slots__ = ("root",)

    def __init__(self, root: str):
        self.root = root


class BuiltinVal:
    __slots__ = ("root", "attr")

    def __init__(self, root: str, attr: str):
        self.root = root
        self.attr = attr


class DtypeVal:
    __slots__ = ("name",)

    def __init__(self, name: str):
        self.name = name


class ConstVal:
    __slots__ = ("value",)

    def __init__(self, value):
        self.value = value


class SymVal:
    """A symbolic host int (an unknown array dim) with product algebra."""

    __slots__ = ("dim",)

    def __init__(self, dim: Dim):
        self.dim = dim


class RangeVal:
    __slots__ = ("start", "stop", "step")

    def __init__(self, start: IVal, stop, step: IVal):
        self.start = start
        self.stop = stop  # IVal | SymVal | TOP-ish
        self.step = step


class StructVal:
    """jax.ShapeDtypeStruct: shape tuple + dtype."""

    __slots__ = ("shape", "dtype")

    def __init__(self, shape, dtype):
        self.shape = shape  # tuple[Dim, ...] | None
        self.dtype = dtype  # str | None


class BlockSpecVal:
    __slots__ = ("block_shape", "index_map")

    def __init__(self, block_shape, index_map):
        self.block_shape = block_shape  # tuple[Dim, ...] | None
        self.index_map = index_map      # FuncVal | None


class PallasVal:
    __slots__ = ("kernel", "grid", "in_specs", "out_specs", "out_shape", "node")

    def __init__(self, kernel, grid, in_specs, out_specs, out_shape, node):
        self.kernel = kernel
        self.grid = grid
        self.in_specs = in_specs
        self.out_specs = out_specs
        self.out_shape = out_shape
        self.node = node


class VmapVal:
    __slots__ = ("func", "in_axes", "out_axes", "node")

    def __init__(self, func, in_axes, out_axes, node):
        self.func = func
        self.in_axes = in_axes
        self.out_axes = out_axes
        self.node = node


class ShapeHandle:
    """``x.shape`` of an array whose rank is unknown: indexing it mints a
    STABLE symbol per (owner, axis), so ``x.shape[0]`` used twice names the
    same dim and symbolic reshape consistency checks can still prove
    coefficient mismatches (``(b, 128) -> (b, 64)``)."""

    __slots__ = ("owner",)

    def __init__(self, owner: str):
        self.owner = owner

    def dim_at(self, i: int) -> Dim:
        return Dim.sym(f"{self.owner}.s{i}")


TOP = IVal()
HOST_TOP = IVal()                      # alias for readability: unbounded host int
TILE_TOP = IVal(tile=True)


def _is_top(v) -> bool:
    return isinstance(v, IVal) and v.lo is None and v.hi is None and not v.dtype


# -- environments -------------------------------------------------------------


class Env:
    __slots__ = ("vars", "parent")

    def __init__(self, parent: "Env | None" = None):
        self.vars: dict[str, Any] = {}
        self.parent = parent

    def get(self, name: str):
        env: Env | None = self
        while env is not None:
            if name in env.vars:
                return env.vars[name]
            env = env.parent
        return None

    def set(self, name: str, value) -> None:
        self.vars[name] = value

    def snapshot(self) -> dict[str, Any]:
        """Deep-enough copy: mutable containers (LVal, TVal-of-LVal) are
        CLONED, so a later in-place ``append`` cannot silently rewrite the
        snapshot — fixpoint change detection and branch-state restoration
        both depend on snapshots being immutable.  A memo preserves
        aliasing within one snapshot."""
        memo: dict[int, Any] = {}
        return {k: _clone_value(v, memo) for k, v in self.vars.items()}


def elem_or_top(lv: "LVal"):
    """An element READ out of a summarised list: bottom (empty) reads as
    TOP — indexing a possibly-empty list proves nothing."""
    e = lv.join_elem()
    return e if e is not None else TOP


def _clone_value(v, memo: dict[int, Any] | None = None):
    if memo is None:
        memo = {}
    if isinstance(v, LVal):
        if id(v) in memo:
            return memo[id(v)]
        out = LVal([_clone_value(e, memo) for e in v.elems]) if v.concrete \
            else LVal(elem=v.elem, length=v.length)
        memo[id(v)] = out
        return out
    if isinstance(v, TVal):
        if id(v) in memo:
            return memo[id(v)]
        out = TVal(tuple(_clone_value(e, memo) for e in v.elems))
        memo[id(v)] = out
        return out
    return v  # IVal & friends are immutable


def _join_values(a, b):
    if a is b:
        return a
    if isinstance(a, IVal) and isinstance(b, IVal):
        return a.join(b)
    if isinstance(a, LVal) and isinstance(b, LVal):
        if a.concrete and b.concrete and len(a.elems) == len(b.elems):
            return LVal([_join_values(x, y) for x, y in zip(a.elems, b.elems)])
        ea, eb = a.join_elem(), b.join_elem()
        elem = eb if ea is None else ea if eb is None else _join_values(ea, eb)
        return LVal(elem=elem, length=a.length.join(b.length))
    if isinstance(a, TVal) and isinstance(b, TVal) and len(a.elems) == len(b.elems):
        return TVal(tuple(_join_values(x, y) for x, y in zip(a.elems, b.elems)))
    if isinstance(a, ConstVal) and isinstance(b, ConstVal) and a.value == b.value:
        return a
    if isinstance(a, (FuncVal, DtypeVal, ModRef, BuiltinVal)) and a is b:
        return a
    tile = getattr(a, "tile", False) or getattr(b, "tile", False)
    return IVal(tile=tile)


def _same_value(a, b) -> bool:
    if a is b:
        return True
    if isinstance(a, IVal) and isinstance(b, IVal):
        return a == b
    if isinstance(a, LVal) and isinstance(b, LVal):
        if a.concrete and b.concrete and len(a.elems) == len(b.elems):
            return all(_same_value(x, y) for x, y in zip(a.elems, b.elems))
        if not a.concrete and not b.concrete:
            return _same_value(a.join_elem(), b.join_elem()) and a.length == b.length
        return False
    if isinstance(a, TVal) and isinstance(b, TVal) and len(a.elems) == len(b.elems):
        return all(_same_value(x, y) for x, y in zip(a.elems, b.elems))
    return False


# -- module model -------------------------------------------------------------


@dataclasses.dataclass
class Assume:
    func: str
    param: str
    lo: int | None
    hi: int | None
    lineno: int
    justified: bool
    text: str


class Module:
    """Parsed kernel module: constants, functions, imports, annotations."""

    def __init__(self, path: str, source: str, loader: "Loader"):
        self.path = path
        self.source = source
        self.loader = loader
        self.tree = ast.parse(source, filename=path)
        self.lines = source.splitlines()
        self.funcs: dict[str, ast.FunctionDef] = {}
        self.imports: dict[str, tuple[str, str]] = {}   # name -> (filepath, orig)
        self.roots: dict[str, str] = {}                 # alias -> builtin root
        self.env = Env()
        self.assumes: dict[str, dict[str, Assume]] = {}  # funcname -> param -> Assume
        self.assume_list: list[Assume] = []
        self.wrapping: dict[int, tuple[bool, str]] = {}  # lineno -> (justified, text)
        self._scope: set[str] | None = None
        self._collect()
        self._parse_annotations()

    # -- construction -------------------------------------------------------

    _ROOT_ALIASES = {
        "jax.numpy": "jnp", "numpy": "np", "jax": "jax", "jax.lax": "lax",
        "jax.experimental.pallas": "pl", "functools": "functools",
        "math": "math", "jax.experimental": "jax.experimental",
    }

    def _collect(self) -> None:
        for stmt in self.tree.body:
            if isinstance(stmt, ast.FunctionDef):
                self.funcs[stmt.name] = stmt
                self.env.set(stmt.name, FuncVal(stmt, self))
            elif isinstance(stmt, ast.ClassDef):
                for sub in stmt.body:
                    if isinstance(sub, ast.FunctionDef):
                        self.funcs[f"{stmt.name}.{sub.name}"] = sub
            elif isinstance(stmt, ast.Import):
                for alias in stmt.names:
                    name = alias.asname or alias.name.split(".")[0]
                    root = self._ROOT_ALIASES.get(alias.name)
                    if root:
                        self.roots[name] = root
            elif isinstance(stmt, ast.ImportFrom):
                self._import_from(stmt)
        # module constants: evaluated AFTER functions/imports are visible
        interp = self.loader.interp
        for stmt in self.tree.body:
            if isinstance(stmt, (ast.Assign, ast.AnnAssign)) and interp is not None:
                try:
                    interp.exec_stmt(stmt, self.env, self, Frame())
                except _Budget:
                    pass

    def _import_from(self, stmt: ast.ImportFrom) -> None:
        modname = stmt.module or ""
        full = self._ROOT_ALIASES.get(modname)
        if full:
            for alias in stmt.names:
                name = alias.asname or alias.name
                # `from jax.experimental import pallas as pl`
                sub = self._ROOT_ALIASES.get(f"{modname}.{alias.name}")
                self.roots[name] = sub or full
            return
        target = self.loader.resolve(self.path, modname, stmt.level)
        if target is None:
            return
        for alias in stmt.names:
            name = alias.asname or alias.name
            self.imports[name] = (target, alias.name)

    def _parse_annotations(self) -> None:
        spans = [(f, f.lineno, f.end_lineno or f.lineno)
                 for f in ast.walk(self.tree) if isinstance(f, ast.FunctionDef)]
        for lineno, line in enumerate(self.lines, start=1):
            m = _WRAPPING_RE.search(line)
            if m:
                just = m.group("just") or ""
                self.wrapping[lineno] = (bool(re.search(r"\w", just)), line.strip())
            m = _ASSUME_RE.search(line)
            if not m:
                continue
            func = None
            best = None
            for f, start, end in spans:
                if start <= lineno <= end and (best is None or end - start < best):
                    func, best = f, end - start
            if func is None:
                continue
            lo = self._eval_bound(m.group("lo"))
            hi = self._eval_bound(m.group("hi"))
            if hi is not None and m.group("close") == ")":
                hi -= 1
            just = m.group("just") or ""
            assume = Assume(func.name, m.group("name"), lo, hi, lineno,
                            bool(re.search(r"\w", just)), line.strip())
            self.assumes.setdefault(func.name, {})[assume.param] = assume
            self.assume_list.append(assume)

    def _eval_bound(self, text: str) -> int | None:
        try:
            expr = ast.parse(text.strip(), mode="eval").body
        except SyntaxError:
            return None
        interp = self.loader.interp
        if interp is None:
            return None
        try:
            v = interp.eval(expr, self.env, self)
        except _Budget:
            return None
        if isinstance(v, IVal) and v.is_const:
            return v.lo
        return None

    # -- scope: tile functions + their transitively-called local helpers ----

    def scope_funcs(self) -> set[str]:
        if self._scope is not None:
            return self._scope
        tile = {n for n, f in self.funcs.items()
                if f.name.endswith(TILE_FUNC_SUFFIXES)}
        grew = True
        while grew:
            grew = False
            called: set[str] = set()
            for name in tile:
                for call in ast.walk(self.funcs[name]):
                    if isinstance(call, ast.Call) and isinstance(call.func, ast.Name):
                        called.add(call.func.id)
            for name in called:
                if name in self.funcs and name not in tile:
                    tile.add(name)
                    grew = True
        self._scope = tile
        return tile


class Loader:
    """Loads/caches kernel modules; resolves relative imports to files."""

    def __init__(self):
        self.modules: dict[str, Module] = {}
        self.interp: "Interp | None" = None

    def get(self, path: str, source: str | None = None) -> Module | None:
        key = str(Path(path))
        if key in self.modules:
            return self.modules[key]
        if source is None:
            p = Path(path)
            if not p.is_file():
                return None
            try:
                source = p.read_text(encoding="utf-8")
            except (OSError, UnicodeDecodeError):
                return None
        try:
            mod = Module(key, source, self)
        except SyntaxError:
            return None
        self.modules[key] = mod
        return mod

    def resolve(self, from_path: str, modname: str, level: int) -> str | None:
        if level == 0:
            return None  # absolute project imports: not needed by kernel code
        base = Path(from_path).parent
        for _ in range(level - 1):
            base = base.parent
        parts = modname.split(".") if modname else []
        cand = base.joinpath(*parts)
        for p in (cand.with_suffix(".py"), cand / "__init__.py"):
            if p.is_file():
                return str(p)
        return None


# -- interpreter --------------------------------------------------------------


class _Budget(Exception):
    pass


class _Break(Exception):
    pass


class _Continue(Exception):
    pass




@dataclasses.dataclass
class Site:
    lineno: int
    op: str
    proved: bool = True
    wrapping: bool = False
    bound: int | None = None
    detail: str = ""

    def absorb(self, math: IVal, ok: bool | None, op: str) -> None:
        self.op = op
        if ok is not True:
            self.proved = False
        hi = math.effective_hi()
        if hi is not None:
            self.bound = hi if self.bound is None else max(self.bound, hi)
        elif ok is not True:
            self.bound = None


@dataclasses.dataclass
class Event:
    rule: str
    path: str
    node: ast.AST
    message: str


class Frame:
    __slots__ = ("ret", "returned", "store_hook")

    def __init__(self, store_hook: Callable | None = None):
        self.ret = None
        self.returned = False
        self.store_hook = store_hook

    def add_return(self, value) -> None:
        self.ret = value if self.ret is None else _join_values(self.ret, value)


class Interp:
    """One abstract-interpretation run over a set of kernel modules."""

    def __init__(self, loader: Loader | None = None):
        self.loader = loader or Loader()
        self.loader.interp = self
        self.summaries: dict[tuple[str, int], Any] = {}
        self.in_progress: set[tuple[str, int]] = set()
        self.sites: dict[tuple[str, int], Site] = {}
        self.events: list[Event] = []
        self.steps = 0
        self.limit = 0
        #: (module path, function) currently being analysed, for site scoping
        self._stack: list[tuple[Module, str, bool]] = []
        self.check_paths: set[str] = set()
        #: set when a break/continue fires under an ABSTRACT condition: the
        #: innermost loop consumes it (save/reset/restore discipline) and
        #: falls back from exact unrolling to the join fixpoint
        self._loop_escape = False
        #: joined env snapshots taken AT those conditional exit points —
        #: the innermost loop joins them into its post-loop state, so a
        #: bound assigned right before a `break` survives even though the
        #: rest of the body (which may re-narrow it) never runs on that path
        self._escape_env: dict[str, Any] | None = None

    # -- public entry points ------------------------------------------------

    def analyze_module(self, path: str, source: str | None = None) -> Module | None:
        mod = self.loader.get(path, source)
        if mod is None:
            return None
        self.check_paths.add(mod.path)
        for name, func in list(mod.funcs.items()):
            self.summary(FuncVal(func, mod))
        return mod

    # -- summaries ----------------------------------------------------------

    def summary(self, fv: FuncVal):
        """Context-insensitive summary: analyse once with contract/TOP seeds."""
        key = (fv.module.path, id(fv.node))
        if key in self.summaries:
            return self.summaries[key]
        if key in self.in_progress:
            return TILE_TOP
        self.in_progress.add(key)
        saved_steps, saved_limit = self.steps, self.limit
        self.steps, self.limit = 0, FUNC_BUDGET
        saved_sites = dict(self.sites)
        saved_events = list(self.events)
        try:
            result = self._run_function(fv)
        except _Budget:
            # partial analysis could claim unsound proofs: demote every site
            # this pass touched, drop its events
            for k, site in self.sites.items():
                if k not in saved_sites or saved_sites[k] is not site:
                    site.proved = False
                    site.detail = "analysis budget exhausted"
            del self.events[len(saved_events):]
            result = TILE_TOP
        finally:
            self.in_progress.discard(key)
            self.steps, self.limit = saved_steps, saved_limit
        self.summaries[key] = result
        return result

    def _run_function(self, fv: FuncVal, args: tuple = (), kwargs=None,
                      store_hook: Callable | None = None):
        func = fv.node
        mod = fv.module
        env = Env(fv.closure if fv.closure is not None else mod.env)
        assumes = mod.assumes.get(getattr(func, "name", ""), {})
        params = self._params(func)
        bound = list(fv.bound_args) + list(args)
        kwargs = {**fv.bound_kwargs, **(kwargs or {})}
        for i, p in enumerate(params):
            if i < len(bound):
                val = bound[i]
            elif p.arg in kwargs:
                val = kwargs[p.arg]
            else:
                val = self._seed_param(p, assumes.get(p.arg))
            env.set(p.arg, val)
        in_scope = (getattr(func, "name", "").endswith(TILE_FUNC_SUFFIXES)
                    or getattr(func, "name", "") in mod.scope_funcs())
        self._stack.append((mod, getattr(func, "name", "<lambda>"), in_scope))
        frame = Frame(store_hook)
        try:
            if isinstance(func, ast.Lambda):
                frame.add_return(self.eval(func.body, env, mod))
            else:
                self.exec_block(func.body, env, mod, frame)
        except (_Break, _Continue):
            pass  # malformed top-level exit: never escape a function frame
        finally:
            self._stack.pop()
        return frame.ret if frame.ret is not None else ConstVal(None)

    @staticmethod
    def _params(func) -> list[ast.arg]:
        a = func.args
        return [*a.posonlyargs, *a.args, *a.kwonlyargs]

    def _seed_param(self, p: ast.arg, assume: Assume | None):
        if assume is not None:
            return IVal.range(assume.lo, assume.hi, None, tile=True)
        ann = p.annotation
        if isinstance(ann, ast.Name) and ann.id in ("int", "bool", "float", "str"):
            return HOST_TOP  # host scalar by annotation (qrlint's exemption)
        return TILE_TOP

    # -- statements ---------------------------------------------------------

    def _tick(self) -> None:
        self.steps += 1
        if self.limit and self.steps > self.limit:
            raise _Budget()

    def exec_block(self, stmts, env: Env, mod: Module, frame: Frame) -> None:
        for stmt in stmts:
            if frame.returned:
                return
            self.exec_stmt(stmt, env, mod, frame)

    def exec_stmt(self, stmt, env: Env, mod: Module, frame: Frame) -> None:
        self._tick()
        if isinstance(stmt, ast.Assign):
            value = self.eval(stmt.value, env, mod)
            for tgt in stmt.targets:
                self.assign(tgt, value, env, mod, frame)
        elif isinstance(stmt, ast.AnnAssign):
            if stmt.value is not None:
                self.assign(stmt.target, self.eval(stmt.value, env, mod), env,
                            mod, frame)
        elif isinstance(stmt, ast.AugAssign):
            cur = self.eval(stmt.target, env, mod)
            rhs = self.eval(stmt.value, env, mod)
            value = self._binop(stmt.op, cur, rhs, stmt, env, mod)
            self.assign(stmt.target, value, env, mod, frame)
        elif isinstance(stmt, ast.Return):
            frame.add_return(self.eval(stmt.value, env, mod)
                             if stmt.value is not None else ConstVal(None))
            frame.returned = True
        elif isinstance(stmt, ast.Expr):
            self.eval(stmt.value, env, mod)
        elif isinstance(stmt, ast.If):
            self._exec_if(stmt, env, mod, frame)
        elif isinstance(stmt, ast.For):
            self._exec_for(stmt, env, mod, frame)
        elif isinstance(stmt, ast.While):
            self._exec_while(stmt, env, mod, frame)
        elif isinstance(stmt, ast.FunctionDef):
            env.set(stmt.name, FuncVal(stmt, mod, closure=env))
        elif isinstance(stmt, ast.With):
            for item in stmt.items:
                val = self.eval(item.context_expr, env, mod)
                if item.optional_vars is not None:
                    self.assign(item.optional_vars, val, env, mod, frame)
            self.exec_block(stmt.body, env, mod, frame)
        elif isinstance(stmt, ast.Try):
            before = env.snapshot()
            self.exec_block(stmt.body, env, mod, frame)
            body_vars = env.snapshot()
            for handler in stmt.handlers:
                env.vars.update(before)
                self.exec_block(handler.body, env, mod, Frame())
                for k, v in env.snapshot().items():
                    if k in body_vars:
                        body_vars[k] = _join_values(body_vars[k], v)
            env.vars.update(body_vars)
            self.exec_block(stmt.finalbody, env, mod, frame)
        elif isinstance(stmt, ast.Raise):
            frame.returned = True
        elif isinstance(stmt, ast.Break):
            raise _Break()
        elif isinstance(stmt, ast.Continue):
            raise _Continue()
        elif isinstance(stmt, (ast.Assert, ast.Pass, ast.Import,
                               ast.ImportFrom, ast.Global, ast.Nonlocal,
                               ast.Delete, ast.ClassDef)):
            pass  # no abstract effect (asserts could refine; stay sound)

    # -- control flow -------------------------------------------------------

    def _exec_if(self, stmt: ast.If, env: Env, mod: Module, frame: Frame) -> None:
        test = self.eval(stmt.test, env, mod)
        if isinstance(test, IVal) and test.is_const:
            branch = stmt.body if test.lo else stmt.orelse
            self.exec_block(branch, env, mod, frame)
            return
        before = env.snapshot()
        then_frame = Frame(frame.store_hook)
        try:
            self.exec_block(stmt.body, env, mod, then_frame)
        except (_Break, _Continue):
            # a CONDITIONAL loop exit: signal the innermost loop (its exact
            # unroll is no longer exact), stash the state AT the exit point
            # (it joins the loop's post-state — the rest of the body never
            # runs on this path and may re-narrow what it assigned), and
            # end the branch for the merge below
            self._note_escape(env)
            then_frame.returned = True
        then_vars, then_returned = env.snapshot(), then_frame.returned
        env.vars.clear()
        env.vars.update(before)
        else_frame = Frame(frame.store_hook)
        try:
            self.exec_block(stmt.orelse, env, mod, else_frame)
        except (_Break, _Continue):
            self._note_escape(env)
            else_frame.returned = True
        if then_frame.ret is not None:
            frame.add_return(then_frame.ret)
        if else_frame.ret is not None:
            frame.add_return(else_frame.ret)
        if then_returned and else_frame.returned:
            frame.returned = True
            return
        if then_returned:       # only the else-path continues
            return
        if else_frame.returned:  # only the then-path continues
            env.vars.clear()
            env.vars.update(then_vars)
            return
        merged = dict(env.vars)
        for k, v in then_vars.items():
            merged[k] = _join_values(merged[k], v) if k in merged else v
        env.vars.clear()
        env.vars.update(merged)

    def _note_escape(self, env: Env) -> None:
        self._loop_escape = True
        snap = env.snapshot()
        if self._escape_env is None:
            self._escape_env = snap
        else:
            merged = dict(snap)
            for k, v in self._escape_env.items():
                merged[k] = _join_values(merged[k], v) if k in merged else v
            self._escape_env = merged

    def _push_loop_scope(self):
        saved = (self._loop_escape, self._escape_env)
        self._loop_escape, self._escape_env = False, None
        return saved

    def _pop_loop_scope(self, saved, env: Env) -> None:
        """Join this loop's conditional-exit states into its post-state,
        then restore the enclosing loop's escape bookkeeping."""
        if self._escape_env:
            for k, v in self._escape_env.items():
                env.vars[k] = _join_values(env.vars[k], v) \
                    if k in env.vars else v
        self._loop_escape, self._escape_env = saved

    def _iter_values(self, iterable) -> tuple[str, Any]:
        """('concrete', [values]) when unrollable, else ('abstract', elem)."""
        if isinstance(iterable, RangeVal):
            s, st = iterable.start, iterable.step
            stop = iterable.stop
            if (isinstance(stop, IVal) and s.is_const and stop.is_const
                    and st.is_const and st.lo):
                vals = [IVal.const(v) for v in range(s.lo, stop.lo, st.lo)]
                if len(vals) <= UNROLL_LIMIT:
                    return "concrete", vals
            # abstract range: the loop variable's bounds depend on the STEP
            # SIGN, and an unknown start/stop side stays unbounded (it is
            # NOT 0 — `range(n, 0, -1)` counts DOWN from n)
            stop_iv = stop if isinstance(stop, IVal) else (
                IVal(0, None) if isinstance(stop, SymVal) else TOP)
            if st.is_const and st.lo is not None and st.lo > 0:
                lo = s.lo
                hi = stop_iv.hi - 1 if stop_iv.hi is not None else None
            elif st.is_const and st.lo is not None and st.lo < 0:
                lo = stop_iv.lo + 1 if stop_iv.lo is not None else None
                hi = s.hi
            else:  # unknown step sign: the hull of both directions
                lo = None if s.lo is None or stop_iv.lo is None else \
                    min(s.lo, stop_iv.lo + 1)
                hi = None if s.hi is None or stop_iv.hi is None else \
                    max(s.hi, stop_iv.hi - 1)
            if lo is not None and hi is not None and lo > hi:
                lo, hi = hi, lo  # degenerate/empty range: keep a valid hull
            return "abstract", IVal.range(lo, hi)
        if isinstance(iterable, LVal):
            if iterable.concrete and len(iterable.elems) <= UNROLL_LIMIT:
                return "concrete", list(iterable.elems)
            return "abstract", elem_or_top(iterable)
        if isinstance(iterable, TVal):
            if len(iterable.elems) <= UNROLL_LIMIT:
                return "concrete", list(iterable.elems)
            return "abstract", _join_values(iterable.elems[0], iterable.elems[-1])
        if isinstance(iterable, IVal):
            return "abstract", IVal(tile=iterable.tile)  # array iteration
        return "abstract", TOP

    def _exec_for(self, stmt: ast.For, env: Env, mod: Module, frame: Frame) -> None:
        mode, data = self._iter_values(self.eval(stmt.iter, env, mod))
        saved = self._push_loop_scope()
        try:
            if mode == "concrete":
                escaped = False
                for item in data:
                    self.assign(stmt.target, item, env, mod, frame)
                    try:
                        self.exec_block(stmt.body, env, mod, frame)
                    except _Continue:
                        continue
                    except _Break:
                        return
                    if frame.returned:
                        return
                    if self._loop_escape:
                        # a break/continue under an abstract condition: the
                        # unroll is no longer exact — re-run as a join
                        # fixpoint over the element join (the partial
                        # unroll's effects are already in env; joining more
                        # only widens, which is sound)
                        escaped = True
                        break
                if not escaped:
                    self.exec_block(stmt.orelse, env, mod, frame)
                    return
                elem = data[0] if data else TOP
                for item in data[1:]:
                    elem = _join_values(elem, item)
                data = elem
            self._fixpoint_loop(stmt.body, env, mod, frame,
                                bind=lambda: self.assign(stmt.target, data,
                                                         env, mod, frame))
            self.exec_block(stmt.orelse, env, mod, frame)
        finally:
            self._pop_loop_scope(saved, env)

    def _exec_while(self, stmt: ast.While, env: Env, mod: Module, frame: Frame) -> None:
        saved = self._push_loop_scope()
        try:
            for _ in range(UNROLL_LIMIT * 8):
                test = self.eval(stmt.test, env, mod)
                if not (isinstance(test, IVal) and test.is_const):
                    break
                if not test.lo:
                    return
                try:
                    self.exec_block(stmt.body, env, mod, frame)
                except _Continue:
                    continue
                except _Break:
                    return
                if frame.returned:
                    return
                if self._loop_escape:
                    break  # conditional exit: fall through to the fixpoint
            self._fixpoint_loop(stmt.body, env, mod, frame)
        finally:
            self._pop_loop_scope(saved, env)

    def _fixpoint_loop(self, body, env: Env, mod: Module, frame: Frame,
                       bind: Callable | None = None) -> None:
        entry = env.snapshot()
        saved = self._push_loop_scope()
        for i in range(FIX_PASSES + 1):
            before = env.snapshot()
            if bind is not None:
                bind()
            try:
                self.exec_block(body, env, mod, frame)
            except (_Break, _Continue):
                pass  # fixpoint state is a join: any exit path is covered
            if frame.returned:
                frame.returned = False  # loop may also not take that path
            changed = []
            for k, v in env.snapshot().items():
                if k not in before or not _same_value(before[k], v):
                    changed.append(k)
                    if k in before:
                        env.vars[k] = _join_values(before[k], v)
            if not changed:
                break
            if i >= FIX_PASSES:  # widen: still-changing names go to TOP
                for k in changed:
                    v = env.vars[k]
                    tile = getattr(v, "tile", True)
                    if isinstance(v, LVal):
                        # the ELEMENT must widen too: a list whose element
                        # bound kept growing would otherwise retain its
                        # last (too-narrow) pass's bound
                        e = v.join_elem()
                        etile = getattr(e, "tile", True) if e is not None else True
                        env.vars[k] = LVal(elem=IVal(tile=bool(etile)),
                                           length=IVal(0, None))
                    else:
                        env.vars[k] = IVal(tile=bool(tile))
                # one more pass so every recorded site OBSERVES the widened
                # state — otherwise a site could keep a stale "proved" bound
                # from the narrow early iterations
                if bind is not None:
                    bind()
                try:
                    self.exec_block(body, env, mod, frame)
                except (_Break, _Continue):
                    pass
                frame.returned = False
                break
        self._pop_loop_scope(saved, env)
        # the loop body may run zero times: join with the entry state
        for k, v in entry.items():
            if k in env.vars:
                env.vars[k] = _join_values(env.vars[k], v)

    # -- assignment ---------------------------------------------------------

    def assign(self, target, value, env: Env, mod: Module, frame: Frame) -> None:
        if isinstance(target, ast.Name):
            env.set(target.id, value)
        elif isinstance(target, ast.Starred):
            self.assign(target.value, value, env, mod, frame)
        elif isinstance(target, (ast.Tuple, ast.List)):
            elems = None
            if isinstance(value, TVal):
                elems = list(value.elems)
            elif isinstance(value, LVal) and value.concrete:
                elems = list(value.elems)
            if elems is not None and len(elems) == len(target.elts) and not any(
                    isinstance(e, ast.Starred) for e in target.elts):
                for t, v in zip(target.elts, elems):
                    self.assign(t, v, env, mod, frame)
            else:
                joined = (elem_or_top(value) if isinstance(value, LVal)
                          else _join_values(value, value) if isinstance(value, TVal)
                          else TOP)
                if isinstance(value, TVal):
                    joined = join_all([e for e in value.elems
                                       if isinstance(e, IVal)]) \
                        if all(isinstance(e, IVal) for e in value.elems) else TOP
                for t in target.elts:
                    self.assign(t, joined, env, mod, frame)
        elif isinstance(target, ast.Subscript):
            self._store_subscript(target, value, env, mod, frame)
        # attribute stores: no abstract effect

    def _store_subscript(self, target: ast.Subscript, value, env: Env,
                         mod: Module, frame: Frame) -> None:
        container = self.eval(target.value, env, mod)
        if frame.store_hook is not None and isinstance(target.value, ast.Name):
            frame.store_hook(target.value.id, value, target)
        idx = self.eval(target.slice, env, mod)
        if isinstance(container, LVal):
            if (container.concrete and isinstance(idx, IVal) and idx.is_const
                    and -len(container.elems) <= idx.lo < len(container.elems)):
                container.elems[idx.lo] = value  # strong update
            elif container.concrete:
                for i in range(len(container.elems)):  # weak update
                    container.elems[i] = _join_values(container.elems[i], value)
            else:
                cur = container.join_elem()
                container.elem = value if cur is None else _join_values(cur, value)
        # array stores (in_ref[i] = v) carry no further abstract effect

    # -- expressions --------------------------------------------------------

    def eval(self, node, env: Env, mod: Module):
        self._tick()
        method = getattr(self, f"_eval_{type(node).__name__}", None)
        if method is not None:
            return method(node, env, mod)
        return TOP

    def _eval_Constant(self, node, env, mod):
        v = node.value
        if isinstance(v, bool):
            return IVal.const(int(v), "bool")
        if isinstance(v, int):
            return IVal.const(v)
        return ConstVal(v)

    def _eval_Name(self, node, env, mod):
        name = node.id
        found = env.get(name)
        if found is not None:
            return found
        if name in mod.roots:
            return ModRef(mod.roots[name])
        if name in mod.imports:
            path, orig = mod.imports[name]
            other = self.loader.get(path)
            if other is not None:
                hit = other.env.get(orig)
                if hit is not None:
                    return hit
                if orig in other.funcs:
                    return FuncVal(other.funcs[orig], other)
            return TOP
        if name in _BUILTINS:
            return BuiltinVal("builtins", name)
        return TOP

    def _eval_Attribute(self, node, env, mod):
        base = self.eval(node.value, env, mod)
        attr = node.attr
        if isinstance(base, ModRef):
            sub = Module._ROOT_ALIASES.get(f"{_ROOT_CANON.get(base.root, base.root)}.{attr}")
            if sub:
                return ModRef(sub)
            if base.root in ("jnp", "np") and attr in _DTYPE_NAMES:
                return DtypeVal(attr)
            if base.root == "jax" and attr == "numpy":
                return ModRef("jnp")
            if base.root == "jax" and attr == "lax":
                return ModRef("lax")
            return BuiltinVal(base.root, attr)
        if isinstance(base, IVal):
            if attr == "shape":
                if base.shape is not None:
                    return TVal(tuple(_dim_value(d) for d in base.shape))
                if isinstance(node.value, ast.Name):
                    fname = self._stack[-1][1] if self._stack else "?"
                    return ShapeHandle(f"{fname}:{node.value.id}")
                return TOP
            if attr == "ndim":
                return IVal.const(len(base.shape)) if base.shape is not None else HOST_TOP
            if attr == "dtype":
                return DtypeVal(base.dtype) if base.dtype else TOP
            if attr == "T":
                shp = tuple(reversed(base.shape)) if base.shape is not None else None
                return dataclasses.replace(base, shape=shp)
            return BoundMethod(base, attr)
        if isinstance(base, StructVal):
            if attr == "shape":
                return TVal(tuple(_dim_value(d) for d in base.shape)) \
                    if base.shape is not None else TOP
            if attr == "dtype":
                return DtypeVal(base.dtype) if base.dtype else TOP
        if isinstance(base, LVal):
            return BoundMethod(base, attr)
        return TOP

    def _eval_BinOp(self, node, env, mod):
        a = self.eval(node.left, env, mod)
        b = self.eval(node.right, env, mod)
        return self._binop(node.op, a, b, node, env, mod)

    def _binop(self, op, a, b, node, env: Env, mod: Module):
        # sequence repetition / concatenation
        if isinstance(op, ast.Mult):
            for seq, n in ((a, b), (b, a)):
                if isinstance(seq, (LVal, TVal)) and isinstance(n, IVal) and n.is_const:
                    if isinstance(seq, TVal):
                        seq = LVal(list(seq.elems))
                    if seq.concrete and 0 <= n.lo * len(seq.elems) <= 4096:
                        return LVal(list(seq.elems) * n.lo)
                    return seq.summarised()
        if isinstance(op, ast.Add):
            if isinstance(a, LVal) and isinstance(b, LVal):
                if a.concrete and b.concrete and len(a.elems) + len(b.elems) <= 4096:
                    return LVal(list(a.elems) + list(b.elems))
                return LVal(elem=_join_values(a.join_elem(), b.join_elem()),
                            length=add(a.length, b.length))
            if isinstance(a, TVal) and isinstance(b, TVal):
                return TVal(a.elems + b.elems)
        if isinstance(a, SymVal) or isinstance(b, SymVal):
            return self._sym_binop(op, a, b)
        if not isinstance(a, IVal) or not isinstance(b, IVal):
            tile = getattr(a, "tile", False) or getattr(b, "tile", False)
            return IVal(tile=tile)
        fn = _TRANSFER.get(type(op))
        if fn is None:
            return IVal(tile=a.tile or b.tile)
        math = fn(a, b)
        dtype = self._result_dtype(a, b)
        float_op = any(d in FLOAT_DTYPES for d in (dtype, a.dtype, b.dtype))
        if isinstance(op, (ast.Mult, ast.LShift)) and (a.tile or b.tile) \
                and not float_op:  # float math rounds, it does not wrap
            self._record_site(node, math, dtype,
                              "*" if isinstance(op, ast.Mult) else "<<")
        ok = math.fits(dtype)
        if ok is True:
            return dataclasses.replace(math, dtype=dtype)
        if dtype in INT_DTYPES:
            return IVal.top(dtype, tile=math.tile)
        return IVal(tile=math.tile)  # unknown dtype, unproven bound

    @staticmethod
    def _result_dtype(a: IVal, b: IVal) -> str | None:
        if a.dtype and b.dtype:
            if a.dtype == b.dtype:
                return a.dtype
            # same-kind integer promotion widens to the bigger operand (jax
            # semantics) — this is what makes `out_ref[...] += wide` stores
            # visible to the accum-dtype hook (the read of the narrow out
            # ref would otherwise erase the accumulated value's dtype)
            if (a.dtype in INT_DTYPES and b.dtype in INT_DTYPES
                    and a.dtype[0] == b.dtype[0] and "bool" not in (a.dtype, b.dtype)):
                wa, wb = DTYPE_WIDTH[a.dtype], DTYPE_WIDTH[b.dtype]
                return a.dtype if wa >= wb else b.dtype
            return None
        if a.dtype and b.dtype is None and not b.tile:
            return a.dtype  # array op host scalar keeps the array dtype
        if b.dtype and a.dtype is None and not a.tile:
            return b.dtype
        return None

    def _sym_binop(self, op, a, b):
        da = a.dim if isinstance(a, SymVal) else (
            Dim.const(a.lo) if isinstance(a, IVal) and a.is_const else None)
        db = b.dim if isinstance(b, SymVal) else (
            Dim.const(b.lo) if isinstance(b, IVal) and b.is_const else None)
        if da is not None and db is not None:
            if isinstance(op, ast.Mult):
                return SymVal(da * db)
            if isinstance(op, ast.FloorDiv) and db.is_const and db.coeff > 0:
                return SymVal(da.floordiv(db.coeff))
        if isinstance(a, SymVal) or isinstance(b, SymVal):
            return IVal(0, None)  # dims are non-negative host ints
        return HOST_TOP

    def _record_site(self, node, math: IVal, dtype: str | None, op: str) -> None:
        if not self._stack:
            return
        mod, _fname, in_scope = self._stack[-1]
        if not in_scope or mod.path not in self.check_paths:
            return
        lineno = getattr(node, "lineno", 0)
        site = self.sites.setdefault((mod.path, lineno), Site(lineno, op))
        if lineno in mod.wrapping:
            site.wrapping = True
        site.absorb(math, math.fits(dtype), op)
        if not site.proved and not site.detail:
            site.detail = f"dtype {dtype or DEFAULT_CHECK_DTYPE}"

    def _eval_UnaryOp(self, node, env, mod):
        v = self.eval(node.operand, env, mod)
        if not isinstance(v, IVal):
            return TOP
        if isinstance(node.op, ast.USub):
            return neg(v)
        if isinstance(node.op, ast.Invert):
            out = invert(v)
            if v.dtype in INT_DTYPES:
                return out.wrapped(v.dtype)
            return out if out.fits(None) is True else IVal(tile=v.tile)
        if isinstance(node.op, ast.Not):
            if v.is_const:
                return IVal.const(0 if v.lo else 1, "bool")
            return IVal.range(0, 1, "bool", v.tile)
        if isinstance(node.op, ast.UAdd):
            return v
        return TOP

    def _eval_BoolOp(self, node, env, mod):
        # `a and b` / `a or b` return an OPERAND, not a bool: the sound
        # abstraction is the join of the possible results
        vals = [self.eval(v, env, mod) for v in node.values]
        ivs = [v for v in vals if isinstance(v, IVal)]
        tile = any(getattr(v, "tile", False) for v in vals)
        if len(ivs) != len(vals):
            return IVal(tile=tile)
        if all(v.is_const for v in ivs):
            acc = ivs[0].lo
            for v in ivs[1:]:
                acc = (acc and v.lo) if isinstance(node.op, ast.And) else (acc or v.lo)
            return IVal.const(int(acc))
        out = join_all(ivs)
        if isinstance(node.op, ast.And):  # may short-circuit to a falsy 0
            out = out.join(IVal.const(0))
        return dataclasses.replace(out, tile=tile)

    def _eval_Compare(self, node, env, mod):
        left = self.eval(node.left, env, mod)
        results: list[IVal] = []
        for op, comp in zip(node.ops, node.comparators):
            right = self.eval(comp, env, mod)
            sym = _CMP_SYMS.get(type(op))
            if sym is not None and isinstance(left, IVal) and isinstance(right, IVal):
                results.append(compare(left, right, sym))
            else:
                tile = getattr(left, "tile", False) or getattr(right, "tile", False)
                results.append(IVal.range(0, 1, "bool", tile))
            left = right
        if len(results) == 1:
            return results[0]
        if all(r.is_const for r in results):  # and-fold of the chain
            return IVal.const(int(all(r.lo for r in results)), "bool")
        return IVal.range(0, 1, "bool", any(r.tile for r in results))

    def _eval_IfExp(self, node, env, mod):
        test = self.eval(node.test, env, mod)
        if isinstance(test, IVal) and test.is_const:
            return self.eval(node.body if test.lo else node.orelse, env, mod)
        return _join_values(self.eval(node.body, env, mod),
                            self.eval(node.orelse, env, mod))

    def _eval_Tuple(self, node, env, mod):
        return TVal(tuple(self._eval_elts(node.elts, env, mod)))

    def _eval_List(self, node, env, mod):
        return LVal(self._eval_elts(node.elts, env, mod))

    def _eval_elts(self, elts, env, mod) -> list:
        out = []
        for e in elts:
            if isinstance(e, ast.Starred):
                mode, data = self._iter_values(self.eval(e.value, env, mod))
                if mode == "concrete":
                    out.extend(data)
                else:
                    out.append(data)
            else:
                out.append(self.eval(e, env, mod))
        return out

    def _eval_Set(self, node, env, mod):
        return LVal(self._eval_elts(node.elts, env, mod)).summarised()

    def _eval_Dict(self, node, env, mod):
        for v in node.values:
            if v is not None:
                self.eval(v, env, mod)
        return TOP

    def _eval_Lambda(self, node, env, mod):
        return FuncVal(node, mod, closure=env)

    def _eval_JoinedStr(self, node, env, mod):
        return ConstVal("")

    def _eval_Slice(self, node, env, mod):
        return TVal((self.eval(node.lower, env, mod) if node.lower else ConstVal(None),
                     self.eval(node.upper, env, mod) if node.upper else ConstVal(None),
                     self.eval(node.step, env, mod) if node.step else ConstVal(None)))

    def _eval_ListComp(self, node, env, mod):
        return self._comp(node, env, mod)

    def _eval_GeneratorExp(self, node, env, mod):
        return self._comp(node, env, mod)

    def _comp(self, node, env, mod):
        gen = node.generators[0]
        mode, data = self._iter_values(self.eval(gen.iter, env, mod))
        frame = Frame()
        sub = Env(env)

        def eval_element() -> Any:
            for cond in gen.ifs:
                self.eval(cond, sub, mod)
            if len(node.generators) > 1:
                inner = ast.GeneratorExp(elt=node.elt,
                                         generators=node.generators[1:])
                v = self._comp(inner, sub, mod)
                return v
            return self.eval(node.elt, sub, mod)

        if mode == "concrete":
            out = []
            for item in data:
                self.assign(gen.target, item, sub, mod, frame)
                v = eval_element()
                if len(node.generators) > 1 and isinstance(v, LVal) and v.concrete:
                    out.extend(v.elems)
                else:
                    out.append(v)
            return LVal(out)
        self.assign(gen.target, data, sub, mod, frame)
        elem = eval_element()
        if isinstance(elem, LVal):
            elem = elem.join_elem()
        return LVal(elem=elem, length=IVal(0, None))

    def _eval_Subscript(self, node, env, mod):
        base = self.eval(node.value, env, mod)
        if isinstance(node.slice, ast.Slice):
            return self._slice(base, node.slice, env, mod)
        idx = self.eval(node.slice, env, mod)
        if isinstance(base, ShapeHandle):
            if isinstance(idx, IVal) and idx.is_const and idx.lo >= 0:
                return SymVal(base.dim_at(idx.lo))
            return IVal(0, None)  # some dim: a non-negative host int
        if isinstance(base, (LVal, TVal)):
            elems = base.elems if isinstance(base, TVal) or base.concrete else None
            if elems is not None and isinstance(idx, IVal) and idx.is_const \
                    and -len(elems) <= idx.lo < len(elems):
                return elems[idx.lo]
            if isinstance(base, LVal):
                return elem_or_top(base)
            return join_all([e for e in base.elems if isinstance(e, IVal)]) \
                if base.elems and all(isinstance(e, IVal) for e in base.elems) else TOP
        if isinstance(base, IVal):
            shape = base.shape[1:] if base.shape else None
            return dataclasses.replace(base, shape=shape or None)
        return TOP

    def _slice(self, base, sl: ast.Slice, env, mod):
        lo = self.eval(sl.lower, env, mod) if sl.lower else None
        hi = self.eval(sl.upper, env, mod) if sl.upper else None
        step = self.eval(sl.step, env, mod) if sl.step else None

        def conc(v, default):
            if v is None or isinstance(v, ConstVal) and v.value is None:
                return default
            if isinstance(v, IVal) and v.is_const:
                return v.lo
            return None

        if isinstance(base, (LVal, TVal)):
            elems = base.elems if isinstance(base, TVal) or base.concrete else None
            if elems is not None:
                a = conc(lo, None)
                b = conc(hi, None)
                s = conc(step, 1)
                if s is not None and (lo is None or a is not None) and \
                        (hi is None or b is not None):
                    sliced = list(elems)[slice(a, b, s)]
                    return TVal(tuple(sliced)) if isinstance(base, TVal) else LVal(sliced)
            if isinstance(base, LVal):
                length = base.length
                b = conc(hi, None)
                if b is not None and b >= 0:
                    length = IVal.range(0, b if length.hi is None else min(length.hi, b))
                return LVal(elem=base.join_elem(), length=length)
            return base
        if isinstance(base, IVal):
            return dataclasses.replace(base, shape=None)
        return TOP

    # -- calls --------------------------------------------------------------

    def _eval_Call(self, node: ast.Call, env, mod):
        func = self.eval(node.func, env, mod)
        args = self._eval_elts(node.args, env, mod)
        kwargs: dict[str, Any] = {}
        for kw in node.keywords:
            if kw.arg is not None:
                kwargs[kw.arg] = self.eval(kw.value, env, mod)
            else:
                self.eval(kw.value, env, mod)
        return self.call(func, args, kwargs, node, env, mod)

    def call(self, func, args: list, kwargs: dict, node, env: Env, mod: Module):
        from . import models  # deferred: models imports this module's classes
        if isinstance(func, BuiltinVal):
            return models.dispatch(self, func, args, kwargs, node, env, mod)
        if isinstance(func, DtypeVal):
            v = args[0] if args else TOP
            return models.cast(v, func.name)
        if isinstance(func, BoundMethod):
            return models.method(self, func, args, kwargs, node, env, mod)
        if isinstance(func, VmapVal):
            from . import shapes
            shapes.check_vmap_call(self, func, args, node, mod)
            tile = any(getattr(a, "tile", False) for a in args)
            return IVal(tile=tile)
        if isinstance(func, PallasVal):
            from . import pallas_checks
            return pallas_checks.check_pallas_invocation(self, func, args, mod)
        if isinstance(func, FuncVal):
            return self._call_user(func, args, kwargs, node, mod)
        tile = any(getattr(a, "tile", False) for a in args)
        return IVal(tile=tile)

    def _call_user(self, fv: FuncVal, args, kwargs, node, mod: Module):
        callee_mod = fv.module
        fname = getattr(fv.node, "name", "<lambda>")
        assumes = callee_mod.assumes.get(fname, {})
        if assumes:
            self._check_contract(fv, args, kwargs, assumes, node, mod)
        # closures/lambdas: inline with actual arguments (their behaviour
        # depends on the captured environment); module-level functions:
        # context-insensitive memoized summary
        if fv.closure is not None or isinstance(fv.node, ast.Lambda) \
                or fv.bound_args or fv.bound_kwargs:
            key = (callee_mod.path, id(fv.node))
            if key in self.in_progress:
                return TILE_TOP
            self.in_progress.add(key)
            try:
                return self._run_function(fv, tuple(args), kwargs)
            except _Budget:
                raise
            finally:
                self.in_progress.discard(key)
        return self.summary(fv)

    def _check_contract(self, fv: FuncVal, args, kwargs, assumes, node, mod) -> None:
        params = self._params(fv.node)
        binding = {}
        for i, p in enumerate(params):
            if i < len(args):
                binding[p.arg] = args[i]
            elif p.arg in kwargs:
                binding[p.arg] = kwargs[p.arg]
        for pname, assume in assumes.items():
            got = binding.get(pname)
            if not isinstance(got, IVal):
                continue
            contract = IVal.range(assume.lo, assume.hi)
            if got.lo is not None and got.hi is not None and (
                    (contract.hi is not None and got.lo > contract.hi)
                    or (contract.lo is not None and got.hi < contract.lo)):
                fname = getattr(fv.node, "name", "<lambda>")
                self.events.append(Event(
                    "kernel-contract-violation", mod.path, node,
                    f"argument {pname!r} of {fname}() is provably in "
                    f"[{got.lo}, {got.hi}], outside the declared contract "
                    f"`{assume.text.split('#', 1)[-1].strip()}`"))


class BoundMethod:
    __slots__ = ("base", "attr")

    def __init__(self, base, attr: str):
        self.base = base
        self.attr = attr


def _dim_value(d: Dim):
    return IVal.const(d.coeff) if d.is_const else SymVal(d)


_ROOT_CANON = {"jnp": "jax.numpy", "np": "numpy", "lax": "jax.lax",
               "pl": "jax.experimental.pallas"}

_DTYPE_NAMES = set(INT_DTYPES) | {"bfloat16", "float16", "float32", "float64"}

_BUILTINS = {
    "len", "range", "int", "float", "bool", "min", "max", "abs", "pow",
    "divmod", "sum", "sorted", "list", "tuple", "zip", "enumerate",
    "reversed", "isinstance", "getattr", "hasattr", "print", "round", "str",
    "repr", "set", "dict", "all", "any", "id", "type",
}

_TRANSFER = {
    ast.Add: add, ast.Sub: sub, ast.Mult: mul, ast.LShift: lshift,
    ast.RShift: rshift, ast.BitAnd: bitand, ast.BitOr: bitor,
    ast.BitXor: bitxor, ast.Mod: mod, ast.FloorDiv: floordiv,
}

_CMP_SYMS = {ast.Lt: "<", ast.Gt: ">", ast.LtE: "<=", ast.GtE: ">=",
             ast.Eq: "==", ast.NotEq: "!="}
