"""Transfer models for builtins, ``jnp``/``np``/``lax``/``pl`` and methods.

Each model is small and conservative: anything unmodeled returns TOP (with
the tile flag propagated), so unknown library surface can only lose
precision, never soundness.  Shape-sensitive constructors/reshapers call
into :mod:`shapes` for the symbolic checks; ``pl.pallas_call`` and
``jax.vmap`` produce first-class values whose *invocation* is checked
(pallas_checks.py / shapes.py).
"""

from __future__ import annotations

import ast
import dataclasses

from .absdom import DTYPE_WIDTH, INT_DTYPES, Dim, IVal, dim_of, join_all
from .interp import (TOP, BlockSpecVal, BoundMethod, BuiltinVal, ConstVal,
                     DtypeVal, Event, FuncVal, LVal, PallasVal, RangeVal,
                     StructVal, SymVal, TVal, VmapVal)


def _tile_of(*vals) -> bool:
    return any(getattr(v, "tile", False) for v in vals)


def _as_ival(v) -> IVal:
    if isinstance(v, IVal):
        return v
    if isinstance(v, SymVal):
        return IVal(0, None)
    return IVal(tile=_tile_of(v))


def cast(v, dtype: str) -> IVal:
    """``astype``/dtype-constructor semantics: keep the interval when it
    provably fits the target, else the target's full range."""
    iv = _as_ival(v)
    if dtype not in INT_DTYPES:
        return IVal(dtype=dtype, tile=iv.tile, shape=iv.shape)
    if iv.fits(dtype) is True:
        return dataclasses.replace(iv, dtype=dtype)
    return dataclasses.replace(IVal.top(dtype, tile=iv.tile), shape=iv.shape)


def _shape_from_value(v) -> tuple | None:
    """A shape tuple of Dims from an abstract shape argument.

    A single *unknown* scalar stays None: an opaque value in shape position
    may itself be a tuple (``batch + (n, m)`` with unknown batch), so
    assuming rank 1 would fabricate provably-wrong ranks."""
    if isinstance(v, (TVal, LVal)):
        elems = v.elems if isinstance(v, TVal) else (v.elems if v.concrete else None)
        if elems is None:
            return None
        return tuple(dim_of(e) for e in elems)
    if isinstance(v, SymVal):
        return (v.dim,)
    if isinstance(v, IVal) and v.is_const:
        return (dim_of(v),)
    return None


def _dtype_from(v) -> str | None:
    if isinstance(v, DtypeVal):
        return v.name
    if isinstance(v, ConstVal) and isinstance(v.value, str):
        return v.value if v.value in DTYPE_WIDTH else None
    return None


# -- python builtins ----------------------------------------------------------


def _b_len(interp, args, kwargs, node, env, mod):
    (v,) = args or (TOP,)
    if isinstance(v, LVal):
        return v.length if not v.concrete else IVal.const(len(v.elems))
    if isinstance(v, TVal):
        return IVal.const(len(v.elems))
    if isinstance(v, IVal) and v.shape:
        return _dim_len(v.shape[0])
    return IVal(0, None)  # len() is a host int, never a tile


def _dim_len(d: Dim):
    return IVal.const(d.coeff) if d.is_const else SymVal(d)


def _b_range(interp, args, kwargs, node, env, mod):
    ivs = [_as_ival(a) if not isinstance(a, SymVal) else a for a in args]
    if len(ivs) == 1:
        return RangeVal(IVal.const(0), ivs[0], IVal.const(1))
    if len(ivs) == 2:
        return RangeVal(ivs[0] if isinstance(ivs[0], IVal) else IVal(0, None),
                        ivs[1], IVal.const(1))
    if len(ivs) == 3:
        return RangeVal(ivs[0] if isinstance(ivs[0], IVal) else IVal(0, None),
                        ivs[1],
                        ivs[2] if isinstance(ivs[2], IVal) else IVal.const(1))
    return RangeVal(IVal.const(0), TOP, IVal.const(1))


def _b_int(interp, args, kwargs, node, env, mod):
    v = args[0] if args else IVal.const(0)
    iv = _as_ival(v)
    return IVal.range(iv.lo, iv.hi)  # host int: loses dtype AND tile


def _b_minmax(is_min):
    def run(interp, args, kwargs, node, env, mod):
        vals = args
        if len(vals) == 1 and isinstance(vals[0], (LVal, TVal, RangeVal)):
            mode, data = interp._iter_values(vals[0])
            vals = data if mode == "concrete" else [data]
        ivs = [_as_ival(v) for v in vals]
        if not ivs:
            return TOP
        out = ivs[0]
        for v in ivs[1:]:
            if is_min:
                lo = None if out.lo is None or v.lo is None else min(out.lo, v.lo)
                hi = None if out.hi is None or v.hi is None else min(out.hi, v.hi)
            else:
                lo = None if out.lo is None or v.lo is None else max(out.lo, v.lo)
                hi = None if out.hi is None or v.hi is None else max(out.hi, v.hi)
            out = IVal.range(lo, hi, None, out.tile or v.tile)
        return out
    return run


def _b_abs(interp, args, kwargs, node, env, mod):
    v = _as_ival(args[0]) if args else TOP
    if v.lo is None or v.hi is None:
        return IVal(0, None, None, v.dtype, v.tile)
    lo = 0 if v.lo <= 0 <= v.hi else min(abs(v.lo), abs(v.hi))
    return IVal.range(lo, max(abs(v.lo), abs(v.hi)), v.dtype, v.tile)


def _b_pow(interp, args, kwargs, node, env, mod):
    ivs = [_as_ival(a) for a in args]
    if len(ivs) >= 2 and all(v.is_const for v in ivs[:3] if v is not None):
        try:
            if len(ivs) == 3:
                return IVal.const(pow(ivs[0].lo, ivs[1].lo, ivs[2].lo))
            if 0 <= ivs[1].lo <= 64 and abs(ivs[0].lo) <= 2**20:
                return IVal.const(pow(ivs[0].lo, ivs[1].lo))
        except (ValueError, ZeroDivisionError):
            return TOP
    return IVal(tile=_tile_of(*args))


def _b_sum(interp, args, kwargs, node, env, mod):
    if args and isinstance(args[0], (LVal, TVal)):
        mode, data = interp._iter_values(args[0])
        if mode == "concrete":
            total = IVal.const(0)
            from .absdom import add
            for v in data:
                total = add(total, _as_ival(v))
            return total
    return IVal(tile=_tile_of(*args))


def _b_zip(interp, args, kwargs, node, env, mod):
    cols = []
    for a in args:
        mode, data = interp._iter_values(a)
        if mode != "concrete":
            elem = TVal(tuple(interp._iter_values(x)[1] for x in args))
            return LVal(elem=elem, length=IVal(0, None))
        cols.append(data)
    n = min((len(c) for c in cols), default=0)
    return LVal([TVal(tuple(c[i] for c in cols)) for i in range(n)])


def _b_enumerate(interp, args, kwargs, node, env, mod):
    if not args:
        return TOP
    mode, data = interp._iter_values(args[0])
    if mode == "concrete":
        return LVal([TVal((IVal.const(i), v)) for i, v in enumerate(data)])
    return LVal(elem=TVal((IVal(0, None), data)), length=IVal(0, None))


def _b_list(interp, args, kwargs, node, env, mod):
    if not args:
        return LVal([])
    mode, data = interp._iter_values(args[0])
    return LVal(list(data)) if mode == "concrete" else LVal(elem=data,
                                                           length=IVal(0, None))


def _b_tuple(interp, args, kwargs, node, env, mod):
    v = _b_list(interp, args, kwargs, node, env, mod)
    return TVal(tuple(v.elems)) if isinstance(v, LVal) and v.concrete else v


def _b_reversed(interp, args, kwargs, node, env, mod):
    if args:
        mode, data = interp._iter_values(args[0])
        if mode == "concrete":
            return LVal(list(reversed(data)))
        return args[0]
    return TOP


def _b_bool_like(interp, args, kwargs, node, env, mod):
    return IVal.range(0, 1, "bool", _tile_of(*args))


_BUILTIN_MODELS = {
    "len": _b_len, "range": _b_range, "int": _b_int, "min": _b_minmax(True),
    "max": _b_minmax(False), "abs": _b_abs, "pow": _b_pow, "sum": _b_sum,
    "zip": _b_zip, "enumerate": _b_enumerate, "list": _b_list,
    "tuple": _b_tuple, "reversed": _b_reversed, "sorted": _b_list,
    "isinstance": _b_bool_like, "hasattr": _b_bool_like, "bool": _b_bool_like,
    "all": _b_bool_like, "any": _b_bool_like,
}


# -- jnp / np / lax / jax / pl ------------------------------------------------


def _j_where(interp, args, kwargs, node, env, mod):
    if len(args) == 3:
        a, b = _as_ival(args[1]), _as_ival(args[2])
        out = a.join(b)
        return dataclasses.replace(out, tile=out.tile or _tile_of(args[0]))
    return IVal(tile=_tile_of(*args))


def _j_minimum(interp, args, kwargs, node, env, mod):
    return _b_minmax(True)(interp, args, kwargs, node, env, mod)


def _j_maximum(interp, args, kwargs, node, env, mod):
    return _b_minmax(False)(interp, args, kwargs, node, env, mod)


def _j_zeros(fill: int | None):
    def run(interp, args, kwargs, node, env, mod):
        shape = _shape_from_value(args[0]) if args else None
        dtype = _dtype_from(kwargs.get("dtype") or (args[1] if len(args) > 1 else None))
        if fill is None:  # jnp.full(shape, value)
            v = _as_ival(args[1]) if len(args) > 1 else TOP
            dtype = _dtype_from(kwargs.get("dtype") or (args[2] if len(args) > 2 else None))
            base = IVal.range(v.lo, v.hi, dtype, True)
        else:
            base = IVal.const(fill, dtype, True) if dtype is None or dtype in INT_DTYPES \
                else IVal(dtype=dtype, tile=True)
        if dtype and dtype not in INT_DTYPES:
            base = IVal(dtype=dtype, tile=True)
        return dataclasses.replace(base, dtype=dtype, shape=shape)
    return run


def _j_like(fill: int | None):
    def run(interp, args, kwargs, node, env, mod):
        src = _as_ival(args[0]) if args else TOP
        if fill is None:  # full_like
            v = _as_ival(args[1]) if len(args) > 1 else TOP
            base = IVal.range(v.lo, v.hi, src.dtype, True)
        elif src.dtype and src.dtype not in INT_DTYPES:
            base = IVal(dtype=src.dtype, tile=True)
        else:
            base = IVal.const(fill, src.dtype, True)
        return dataclasses.replace(base, shape=src.shape)
    return run


def _j_arange(interp, args, kwargs, node, env, mod):
    ivs = [_as_ival(a) for a in args]
    dtype = _dtype_from(kwargs.get("dtype"))
    if len(ivs) == 1 and ivs[0].hi is not None:
        n = ivs[0]
        shape = (dim_of(n),) if n.is_const else None
        return IVal.range(0, max(n.hi - 1, 0), dtype, True) if dtype is None or \
            dtype in INT_DTYPES else IVal(dtype=dtype, tile=True, shape=shape)
    return IVal(tile=True, dtype=dtype)


def _j_pad(interp, args, kwargs, node, env, mod):
    src = _as_ival(args[0]) if args else TOP
    fill = kwargs.get("constant_values")
    if fill is None and len(args) <= 2 and not kwargs.get("mode"):
        out = src.join(IVal.const(0))  # default zero padding joins 0
    elif isinstance(fill, IVal):
        out = src.join(fill)
    else:  # non-constant fill / edge modes: values stay within src for
        # edge/reflect, but be conservative about anything unmodeled
        out = src.join(_as_ival(fill)) if fill is not None else IVal(tile=True)
    return dataclasses.replace(out, dtype=src.dtype, tile=True, shape=None)


def _j_reshape(interp, args, kwargs, node, env, mod):
    from . import shapes
    src = _as_ival(args[0]) if args else TOP
    dim_args = args[1:]
    if len(dim_args) == 1:
        # a single argument may be a full shape tuple (possibly opaque)
        shp = _shape_from_value(dim_args[0])
    elif dim_args:
        # multiple arguments are scalar dims by signature: rank is known
        shp = tuple(dim_of(a) for a in dim_args)
    else:
        shp = None
    new_shape = shapes.check_reshape(interp, src, shp, node, mod)
    return dataclasses.replace(src, shape=new_shape)


def _j_concatenate(interp, args, kwargs, node, env, mod):
    from . import shapes
    parts = []
    if args and isinstance(args[0], (LVal, TVal)):
        mode, data = interp._iter_values(args[0])
        parts = data if mode == "concrete" else []
    axis = kwargs.get("axis") or (args[1] if len(args) > 1 else None)
    axis_c = axis.lo if isinstance(axis, IVal) and axis.is_const else 0
    new_shape = shapes.check_concatenate(interp, parts, axis_c, node, mod)
    ivs = [_as_ival(p) for p in parts]
    out = join_all(ivs) if ivs else TOP
    return dataclasses.replace(out, tile=True, shape=new_shape)


def _j_stack(interp, args, kwargs, node, env, mod):
    parts = []
    if args and isinstance(args[0], (LVal, TVal)):
        mode, data = interp._iter_values(args[0])
        parts = data if mode == "concrete" else []
    ivs = [_as_ival(p) for p in parts]
    out = join_all(ivs) if ivs else TOP
    return dataclasses.replace(out, tile=True, shape=None)


def _j_transpose(interp, args, kwargs, node, env, mod):
    from . import shapes
    src = _as_ival(args[0]) if args else TOP
    axes = kwargs.get("axes") or (args[1] if len(args) > 1 else None)
    new_shape = shapes.check_transpose(interp, src, axes, node, mod)
    return dataclasses.replace(src, shape=new_shape)


def _j_swapaxes(interp, args, kwargs, node, env, mod):
    from . import shapes
    src = _as_ival(args[0]) if args else TOP
    new_shape = shapes.check_swapaxes(
        interp, src,
        args[1] if len(args) > 1 else None,
        args[2] if len(args) > 2 else None, node, mod)
    return dataclasses.replace(src, shape=new_shape)


def _j_matmul(interp, args, kwargs, node, env, mod):
    from . import shapes
    a = _as_ival(args[0]) if args else TOP
    b = _as_ival(args[1]) if len(args) > 1 else TOP
    _check_accum_dtype(interp, (a, b), kwargs, node, mod)
    new_shape = shapes.check_matmul(interp, a, b, node, mod)
    return IVal(dtype=None, tile=True, shape=new_shape)


def _j_dot_general(interp, args, kwargs, node, env, mod):
    a = _as_ival(args[0]) if args else TOP
    b = _as_ival(args[1]) if len(args) > 1 else TOP
    _check_accum_dtype(interp, (a, b), kwargs, node, mod)
    return IVal(tile=True)


def _check_accum_dtype(interp, operands, kwargs, node, mod) -> None:
    pref = _dtype_from(kwargs.get("preferred_element_type"))
    if pref is None:
        return
    widths = [DTYPE_WIDTH.get(v.dtype) for v in operands if v.dtype]
    if widths and DTYPE_WIDTH.get(pref, 0) < max(widths):
        interp.events.append(Event(
            "kernel-accum-dtype", mod.path, node,
            f"preferred_element_type={pref} is narrower than the "
            f"{max(widths)}-bit operands: the contraction accumulates in a "
            "narrower type than its inputs and loses precision/overflows"))


def _j_reduce(interp, args, kwargs, node, env, mod):
    src = _as_ival(args[0]) if args else TOP
    return IVal(tile=src.tile or True)


def _j_reduce_minmax(interp, args, kwargs, node, env, mod):
    src = _as_ival(args[0]) if args else TOP
    return dataclasses.replace(src, shape=None)  # element range is preserved


def _j_asarray(interp, args, kwargs, node, env, mod):
    v = args[0] if args else TOP
    dtype = _dtype_from(kwargs.get("dtype") or (args[1] if len(args) > 1 else None))
    if isinstance(v, (LVal, TVal)):
        mode, data = interp._iter_values(v)
        ivs = [_as_ival(x) for x in (data if mode == "concrete" else [data])]
        out = join_all(ivs) if ivs else TOP
        shape = (Dim.const(len(data)),) if mode == "concrete" else None
        out = dataclasses.replace(out, tile=True, shape=shape)
    else:
        out = dataclasses.replace(_as_ival(v), tile=True)
    return cast(out, dtype) if dtype else out


def _j_bit(interp_op):
    def run(interp, args, kwargs, node, env, mod):
        a = _as_ival(args[0]) if args else TOP
        b = _as_ival(args[1]) if len(args) > 1 else TOP
        return interp._binop(interp_op(), a, b, node, env, mod)
    return run


def _jax_jit(interp, args, kwargs, node, env, mod):
    if args and isinstance(args[0], FuncVal):
        fv = args[0]
        donate = ()
        dn = kwargs.get("donate_argnums")
        if isinstance(dn, IVal) and dn.is_const:
            donate = (dn.lo,)
        elif isinstance(dn, (TVal, LVal)):
            mode, data = interp._iter_values(dn)
            if mode == "concrete":
                donate = tuple(d.lo for d in data
                               if isinstance(d, IVal) and d.is_const)
        return FuncVal(fv.node, fv.module, fv.closure, fv.bound_args,
                       fv.bound_kwargs, jitted=True, donate=donate)
    return args[0] if args else TOP


def _jax_vmap(interp, args, kwargs, node, env, mod):
    func = args[0] if args else None
    in_axes = kwargs.get("in_axes") or (args[1] if len(args) > 1 else None)
    out_axes = kwargs.get("out_axes") or (args[2] if len(args) > 2 else None)
    return VmapVal(func, in_axes, out_axes, node)


def _lax_cond(interp, args, kwargs, node, env, mod):
    outs = []
    for branch in args[1:3]:
        if isinstance(branch, FuncVal):
            outs.append(interp.summary(branch))
    ivs = [o for o in outs if isinstance(o, IVal)]
    return join_all(ivs) if ivs and len(ivs) == len(outs) else IVal(tile=True)


def _lax_select(interp, args, kwargs, node, env, mod):
    if len(args) == 3:
        return _as_ival(args[1]).join(_as_ival(args[2]))
    return IVal(tile=True)


def _pl_pallas_call(interp, args, kwargs, node, env, mod):
    from . import pallas_checks
    kernel = args[0] if args else None
    pv = PallasVal(
        kernel if isinstance(kernel, FuncVal) else None,
        kwargs.get("grid"), kwargs.get("in_specs"), kwargs.get("out_specs"),
        kwargs.get("out_shape"), node)
    pallas_checks.check_pallas_static(interp, pv, mod)
    return pv


def _pl_blockspec(interp, args, kwargs, node, env, mod):
    block = args[0] if args else kwargs.get("block_shape")
    index_map = args[1] if len(args) > 1 else kwargs.get("index_map")
    return BlockSpecVal(_shape_from_value(block) if block is not None else None,
                        index_map if isinstance(index_map, FuncVal) else None)


def _jax_struct(interp, args, kwargs, node, env, mod):
    shape = args[0] if args else kwargs.get("shape")
    dtype = args[1] if len(args) > 1 else kwargs.get("dtype")
    return StructVal(_shape_from_value(shape) if shape is not None else None,
                     _dtype_from(dtype))


_JNP_MODELS = {
    "where": _j_where, "minimum": _j_minimum, "maximum": _j_maximum,
    "zeros": _j_zeros(0), "ones": _j_zeros(1), "full": _j_zeros(None),
    "empty": _j_zeros(0), "zeros_like": _j_like(0), "ones_like": _j_like(1),
    "full_like": _j_like(None), "empty_like": _j_like(0),
    "arange": _j_arange, "pad": _j_pad, "reshape": _j_reshape,
    "concatenate": _j_concatenate, "stack": _j_stack, "vstack": _j_stack,
    "hstack": _j_stack, "transpose": _j_transpose, "swapaxes": _j_swapaxes,
    "matmul": _j_matmul, "dot": _j_matmul, "asarray": _j_asarray,
    "array": _j_asarray, "sum": _j_reduce, "prod": _j_reduce,
    "min": _j_reduce_minmax, "max": _j_reduce_minmax, "abs": _b_abs,
    "mod": _j_bit(ast.Mod), "remainder": _j_bit(ast.Mod),
    "left_shift": _j_bit(ast.LShift), "right_shift": _j_bit(ast.RShift),
    "bitwise_and": _j_bit(ast.BitAnd), "bitwise_or": _j_bit(ast.BitOr),
    "bitwise_xor": _j_bit(ast.BitXor), "uint32": None, "int32": None,
}

_ROOT_MODELS = {
    ("jax", "jit"): _jax_jit, ("jax", "vmap"): _jax_vmap,
    ("jax", "ShapeDtypeStruct"): _jax_struct,
    ("lax", "cond"): _lax_cond, ("lax", "select"): _lax_select,
    ("lax", "dot_general"): _j_dot_general,
    ("pl", "pallas_call"): _pl_pallas_call, ("pl", "BlockSpec"): _pl_blockspec,
    ("functools", "reduce"): None,
}


def dispatch(interp, func: BuiltinVal, args, kwargs, node, env, mod):
    root, attr = func.root, func.attr
    if root == "builtins":
        model = _BUILTIN_MODELS.get(attr)
        if model is not None:
            return model(interp, args, kwargs, node, env, mod)
        if attr in ("float", "str", "repr", "print", "round", "id", "type",
                    "getattr", "divmod", "set", "dict"):
            return TOP
        return IVal(tile=_tile_of(*args))
    if root == "functools" and attr == "partial":
        if args and isinstance(args[0], (FuncVal, BuiltinVal)):
            target = args[0]
            if isinstance(target, FuncVal):
                return FuncVal(target.node, target.module, target.closure,
                               target.bound_args + tuple(args[1:]),
                               {**target.bound_kwargs, **kwargs},
                               target.jitted, target.donate)
            # functools.partial(jax.jit, ...) used as a decorator factory
            return target
        return TOP
    if root in ("jnp", "np"):
        from .absdom import INT_DTYPES as _ID
        if attr in _ID or attr in ("bfloat16", "float16", "float32", "float64"):
            return cast(args[0] if args else TOP, attr)
        model = _JNP_MODELS.get(attr)
        if model is not None:
            return model(interp, args, kwargs, node, env, mod)
        return IVal(tile=_tile_of(*args) or root == "jnp")
    model = _ROOT_MODELS.get((root, attr))
    if model is not None:
        return model(interp, args, kwargs, node, env, mod)
    if root == "lax":
        return IVal(tile=True)
    return IVal(tile=_tile_of(*args))


# -- bound methods ------------------------------------------------------------


def method(interp, bm: BoundMethod, args, kwargs, node, env, mod):
    base, attr = bm.base, bm.attr
    if isinstance(base, LVal):
        if attr == "append":
            if base.concrete and len(base.elems) < 4096:
                base.elems.append(args[0] if args else TOP)
            else:
                from .interp import _join_values
                cur = base.join_elem()
                item = args[0] if args else TOP
                base.elems = None
                base.elem = item if cur is None else _join_values(cur, item)
                base.length = IVal(0, None)
            return ConstVal(None)
        if attr == "extend" and args:
            mode, data = interp._iter_values(args[0])
            if base.concrete and mode == "concrete" and \
                    len(base.elems) + len(data) <= 4096:
                base.elems.extend(data)
            else:
                from .interp import _join_values
                other = (args[0].join_elem() if isinstance(args[0], LVal)
                         else TOP)
                cur = base.join_elem()
                if cur is None:
                    base.elem = other
                elif other is None:
                    base.elem = cur
                else:
                    base.elem = _join_values(cur, other)
                base.elems = None
                base.length = IVal(0, None)
            return ConstVal(None)
        if attr == "pop":
            if base.concrete and base.elems:
                return base.elems.pop()
            from .interp import elem_or_top
            return elem_or_top(base)
        if attr == "copy":
            return LVal(list(base.elems)) if base.concrete else base
        return TOP
    if isinstance(base, IVal):
        if attr == "astype":
            dt = _dtype_from(args[0] if args else kwargs.get("dtype"))
            return cast(base, dt) if dt else dataclasses.replace(base, dtype=None)
        if attr == "reshape":
            return _j_reshape(interp, [base, *args], kwargs, node, env, mod)
        if attr == "transpose":
            a = args[0] if len(args) == 1 else (TVal(tuple(args)) if args else None)
            return _j_transpose(interp, [base, a] if a is not None else [base],
                                kwargs, node, env, mod)
        if attr == "swapaxes":
            return _j_swapaxes(interp, [base, *args], kwargs, node, env, mod)
        if attr in ("sum", "prod", "mean", "dot"):
            return IVal(tile=base.tile)
        if attr in ("min", "max"):
            return dataclasses.replace(base, shape=None)
        if attr in ("item", "tolist"):
            return IVal.range(base.lo, base.hi)  # host value
        if attr in ("copy", "block_until_ready", "squeeze", "ravel", "flatten"):
            return dataclasses.replace(base, shape=None)
        if attr == "bit_length":
            return IVal(0, None)
        return IVal(tile=base.tile)
    return TOP
