"""qrkernel analysis pack, exposed as qrlint ``Rule`` objects.

One :class:`KernelAnalysis` per project run (abstract interpretation →
value-range sites + shape/pallas/contract events, plus the AST-only
donation/recompile pass), cached on the ``Project``; the thin rule classes
each publish one finding id from it so ``--select``/``--ignore`` and the
inline suppression machinery work unchanged.

Rule ids:

==========================  =================================================
kernel-int32-overflow       a ``*``/``<<`` on kernel tile values whose
                            mathematical interval cannot be proven to fit the
                            value's dtype (int32 when unknown) and that is
                            not annotated ``# qrkernel: wrapping``
kernel-contract-violation   a call argument provably outside a declared
                            ``# qrkernel: assume`` parameter contract
kernel-shape-mismatch       reshape/concatenate/matmul with provably
                            inconsistent symbolic element counts or dims
kernel-batch-axis           vmap in_axes/transpose axis bookkeeping loses or
                            misnames a batch axis (out-of-range, duplicated,
                            arity mismatch)
kernel-grid-blockspec       pallas_call grid × BlockSpec inconsistency:
                            non-divisible block dims or an index_map that
                            provably reaches out of bounds
kernel-accum-dtype          an accumulator/output dtype narrower than the
                            values stored into it (incl.
                            preferred_element_type on contractions)
kernel-read-after-donate    an operand read after being passed in a
                            donate_argnums position
kernel-recompile-hazard     a jitted callable invoked in a loop with a
                            loop-dependent argument shape (recompile storm)
kernel-unjustified-annotation  a qrkernel suppression / ``wrapping`` /
                            ``assume`` annotation with no one-line
                            justification
==========================  =================================================

File scope: the analysis runs on files that import jax and look
kernel-shaped (pallas / ``*_tiles``/``*_kernel`` functions / vmap / jit /
donation) — the modules named by docs/static_analysis.md plus any fixture
that matches.
"""

from __future__ import annotations

import ast
import re

from ..engine import FileContext, Project, Rule
from ..rules_jax import _imports_jax
from . import dataflow
from .interp import _ASSUME_RE, _WRAPPING_RE, Interp

_SUPPRESS_RE = re.compile(
    r"#\s*(?:qrlint|qrkernel|qrproto|qrlife):\s*disable(?:-file)?\s*=\s*"
    r"(?P<rules>[\w.,\- ]+)(?P<rest>.*)$")


def kernel_file(ctx: FileContext) -> bool:
    """Any jax-importing file is in scope: the value-range sites are still
    restricted to tile functions, but shape/vmap/pallas/donation mistakes
    live in plain jnp code too (kem/, sig/, provider glue)."""
    return _imports_jax(ctx)


class KernelAnalysis:
    """All qrkernel findings for one project, computed once and cached."""

    def __init__(self, project: Project):
        self.project = project
        self.interp = Interp()
        self.findings: list[tuple[str, FileContext, object, str]] = []
        self.sites = {}
        checked: list[FileContext] = []
        for ctx in project.contexts.values():
            if kernel_file(ctx):
                mod = self.interp.analyze_module(ctx.path, ctx.source)
                if mod is not None:
                    checked.append(ctx)
        self.sites = self.interp.sites
        self._collect_site_findings(project)
        self._collect_events(project)
        self._collect_dataflow(checked)
        KernelAnalysis.last = self

    #: most recent analysis in this process, so the CLI's --proofs ledger
    #: can reuse the instance the engine run just computed instead of
    #: re-interpreting the whole tree
    last: "KernelAnalysis | None" = None

    @classmethod
    def of(cls, project: Project) -> "KernelAnalysis":
        cached = getattr(project, "_qrkernel_analysis", None)
        if cached is None:
            cached = cls(project)
            project._qrkernel_analysis = cached  # type: ignore[attr-defined]
        return cached

    def _ctx(self, path: str) -> FileContext | None:
        return self.project.contexts.get(path)

    def _collect_site_findings(self, project: Project) -> None:
        for (path, lineno), site in sorted(self.interp.sites.items()):
            if site.proved or site.wrapping:
                continue
            ctx = self._ctx(path)
            if ctx is None:
                continue
            detail = f" ({site.detail})" if site.detail else ""
            self.findings.append((
                "kernel-int32-overflow", ctx, _LineNode(lineno),
                f"`{site.op}` on kernel tile values: interval analysis cannot "
                f"prove the result fits its vector-register dtype{detail}; "
                "widen/restructure, declare a `# qrkernel: assume` parameter "
                "contract the proof can start from, or annotate "
                "`# qrkernel: wrapping — why` if wrap is by design"))

    def _collect_events(self, project: Project) -> None:
        seen: set[tuple] = set()
        for ev in self.interp.events:
            ctx = self._ctx(ev.path)
            if ctx is None:
                continue
            key = (ev.rule, ev.path, getattr(ev.node, "lineno", 0),
                   getattr(ev.node, "col_offset", 0), ev.message)
            if key in seen:
                continue
            seen.add(key)
            self.findings.append((ev.rule, ctx, ev.node, ev.message))

    def _collect_dataflow(self, checked: list[FileContext]) -> None:
        seen: set[tuple] = set()
        for ctx in checked:
            for ev in dataflow.analyze_dataflow(ctx.tree):
                # nested FunctionDefs are walked by both themselves and
                # their enclosing function: dedupe per site
                key = (ev.rule, ctx.path, getattr(ev.node, "lineno", 0),
                       getattr(ev.node, "col_offset", 0), ev.message)
                if key in seen:
                    continue
                seen.add(key)
                self.findings.append((ev.rule, ctx, ev.node, ev.message))

    # -- proof reporting (CLI --proofs, docs) -------------------------------

    def proofs(self) -> list[dict]:
        out = []
        for (path, lineno), site in sorted(self.interp.sites.items()):
            status = ("wrapping" if site.wrapping
                      else "proved" if site.proved else "unproven")
            entry = {"path": path, "line": lineno, "op": site.op,
                     "status": status}
            if site.proved and site.bound is not None:
                entry["bound_bits"] = max(site.bound, 1).bit_length()
                entry["bound"] = site.bound
            out.append(entry)
        return out


class _KernelRule(Rule):
    """Base: publish one finding id out of the shared analysis."""

    severity = "error"

    def check_project(self, project: Project) -> None:
        analysis = KernelAnalysis.of(project)
        for rule_id, ctx, node, message in analysis.findings:
            if rule_id == self.id:
                project.report(self, ctx, node, message)


class Int32OverflowRule(_KernelRule):
    id = "kernel-int32-overflow"
    description = ("a */<< on kernel tile values whose interval cannot be "
                   "proven to fit the register dtype (wrap-silent overflow); "
                   "machine-checks what int32-narrowing suppressions claimed")


class ContractViolationRule(_KernelRule):
    id = "kernel-contract-violation"
    description = ("a call argument provably outside the callee's declared "
                   "`# qrkernel: assume` parameter contract")


class ShapeMismatchRule(_KernelRule):
    id = "kernel-shape-mismatch"
    description = ("reshape/concatenate/matmul with provably inconsistent "
                   "symbolic element counts or dims")


class BatchAxisRule(_KernelRule):
    id = "kernel-batch-axis"
    description = ("vmap/transpose batch-axis bookkeeping error: "
                   "out-of-range or duplicated axis, in_axes arity mismatch")


class GridBlockSpecRule(_KernelRule):
    id = "kernel-grid-blockspec"
    description = ("pallas_call grid x BlockSpec inconsistency: non-divisible "
                   "block dims or an out-of-bounds index_map")


class AccumDtypeRule(_KernelRule):
    id = "kernel-accum-dtype"
    description = ("accumulator/output dtype narrower than the values stored "
                   "into it (silent truncation)")


class ReadAfterDonateRule(_KernelRule):
    id = "kernel-read-after-donate"
    description = ("an operand is read after being passed in a donate_argnums "
                   "position (the buffer is aliased to the output)")


class RecompileHazardRule(_KernelRule):
    id = "kernel-recompile-hazard"
    description = ("a jitted callable invoked in a loop with a loop-dependent "
                   "argument shape: every iteration recompiles")


class UnjustifiedAnnotationRule(Rule):
    """qrkernel suppressions AND semantic annotations (``wrapping`` /
    ``assume``) require a one-line justification, policed exactly like
    qrflow's suppressions: a waiver nobody can read is a human claim again."""

    id = "kernel-unjustified-annotation"
    severity = "error"
    description = ("a qrkernel suppression / wrapping / assume annotation "
                   "carries no one-line justification")

    _POLICED = frozenset({
        "kernel-int32-overflow", "kernel-contract-violation",
        "kernel-shape-mismatch", "kernel-batch-axis", "kernel-grid-blockspec",
        "kernel-accum-dtype", "kernel-read-after-donate",
        "kernel-recompile-hazard", "kernel-unjustified-annotation",
    })

    def check_project(self, project: Project) -> None:
        for ctx in project.contexts.values():
            for lineno, comment in _comments(ctx):
                self._check_line(project, ctx, lineno, comment)

    def _check_line(self, project: Project, ctx: FileContext, lineno: int,
                    line: str) -> None:
        m = _WRAPPING_RE.search(line)
        if m and not re.search(r"\w", m.group("just") or ""):
            project.report(
                self, ctx, _LineNode(lineno),
                "`# qrkernel: wrapping` annotation has no justification — "
                "state WHY the wrap is by design (e.g. `— uint32 lane "
                "rotation: shifted-out bits recovered from the partner "
                "word`)")
            return
        m = _ASSUME_RE.search(line)
        if m and not re.search(r"\w", m.group("just") or ""):
            project.report(
                self, ctx, _LineNode(lineno),
                f"`# qrkernel: assume {m.group('name')} in …` contract has "
                "no justification — cite the spec fact that makes the "
                "precondition true (e.g. `— FIPS 204: NTT operands are "
                "mod-q residues`)")
            return
        m = _SUPPRESS_RE.search(line)
        if not m:
            return
        blob, rest = m.group("rules"), m.group("rest") or ""
        sep = re.search(r"[^\w,\- ]", blob)
        ids_part = blob[: sep.start()] if sep else blob
        justification = (blob[sep.start():] if sep else "") + rest
        ids = {tok for part in ids_part.split(",")
               for tok in part.strip().split() if tok}
        kernel_ids = ids & self._POLICED
        if kernel_ids and not re.search(r"\w", justification):
            project.report(
                self, ctx, _LineNode(lineno),
                f"suppression of {', '.join(sorted(kernel_ids))} has no "
                "justification — append one after the rule id "
                "(e.g. `# qrkernel: disable=kernel-recompile-hazard — "
                "cold path, one-off trace`)")


class _LineNode:
    """Minimal AST-node stand-in so line-anchored findings route through
    the normal report/suppression machinery."""

    def __init__(self, lineno: int):
        self.lineno = lineno
        self.end_lineno = lineno
        self.col_offset = 0


def _comments(ctx: FileContext) -> list[tuple[int, str]]:
    """Real COMMENT tokens only — annotation syntax quoted inside a
    docstring or an error-message string must not be policed."""
    import io
    import tokenize

    out: list[tuple[int, str]] = []
    try:
        for tok in tokenize.generate_tokens(io.StringIO(ctx.source).readline):
            if tok.type == tokenize.COMMENT:
                out.append((tok.start[0], tok.string))
    except (tokenize.TokenError, IndentationError, SyntaxError):
        # fall back to raw lines on tokenizer trouble (never silently skip)
        out = list(enumerate(ctx.lines, start=1))
    return out


KERNEL_RULES = (
    Int32OverflowRule, ContractViolationRule, ShapeMismatchRule,
    BatchAxisRule, GridBlockSpecRule, AccumDtypeRule, ReadAfterDonateRule,
    RecompileHazardRule, UnjustifiedAnnotationRule,
)


# -- single-file interval API (qrlint's int32-narrowing defers to this) -------

_STATUS_CACHE: dict[tuple[str, int], dict[int, str]] = {}


def site_status(path: str, source: str) -> dict[int, str]:
    """``{lineno: 'proved' | 'wrapping'}`` for one kernel module's ``*``/``<<``
    sites — the machine-checked facts qrlint's ``int32-narrowing`` rule
    defers to.  Sites the interval analysis cannot prove are absent (qrlint
    keeps flagging them).  Cached per (path, source)."""
    key = (path, hash(source))
    if key in _STATUS_CACHE:
        return _STATUS_CACHE[key]
    interp = Interp()
    out: dict[int, str] = {}
    try:
        mod = interp.loader.get(path, source)
        if mod is not None:
            interp.check_paths.add(mod.path)
            from .interp import FuncVal
            for name in mod.scope_funcs():
                func = mod.funcs.get(name)
                if func is not None:
                    interp.summary(FuncVal(func, mod))
            for (p, lineno), site in interp.sites.items():
                if p != mod.path:
                    continue
                if site.wrapping:
                    out[lineno] = "wrapping"
                elif site.proved:
                    out[lineno] = "proved"
    except (SyntaxError, RecursionError):
        out = {}
    _STATUS_CACHE[key] = out
    return out
