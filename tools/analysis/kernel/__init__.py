"""qrkernel — abstract-interpretation verifier for the JAX/Pallas kernel layer.

The two sibling analyzers stop at the device boundary: qrlint's
``int32-narrowing`` can only *flag* multiply/shift sites in Pallas tile
code (PR 1 closed them with hand-written "31-bit bound" suppression
comments — human claims no tool checks), and qrflow's taint lattice never
looks inside a jitted program.  qrkernel is the third ratchet: an abstract
interpreter (pure AST, no jax import — runs on minimal images) over the
kernel modules with four analyses:

* **value-range / bit-width** (absdom.py + interp.py) — integer interval +
  known-bits domain propagated through jnp ops, shifts, masks and dtype
  casts, seeded from byte/modulus facts (``x & 0xFF`` → [0, 255], ML-KEM
  q=3329, ML-DSA q=8380417) and declared ``# qrkernel: assume`` parameter
  contracts; proves every flagged multiply/shift fits its dtype and turns
  wrap-by-design sites (Keccak rotations) into explicit, policed
  ``# qrkernel: wrapping — why`` annotations instead of disables.
* **symbolic shape / batch-axis** (shapes.py) — shapes as symbolic product
  normal forms through reshape/concatenate/matmul/indexing and vmap axis
  bookkeeping; only provable inconsistencies fire.
* **Pallas structural** (pallas_checks.py) — grid × BlockSpec divisibility,
  index-map bounds vs array shape, accumulator-dtype narrowing.
* **donation / recompile-hazard** (dataflow.py) — reads after a
  ``donate_argnums`` operand is aliased away; loop-dependent shapes
  reaching jitted callables (recompile storms).

qrlint's ``int32-narrowing`` rule *defers* to qrkernel's interval results
in kernel modules (``packs.site_status``), so the old suppression comments
become machine-checked facts and the live-tree suppression count drops.

Run: ``python -m tools.analysis.kernel.run quantum_resistant_p2p_tpu`` (or
the ``qrkernel`` console script).  Docs: docs/static_analysis.md.
"""

from __future__ import annotations

from ..engine import Rule


def kernel_rules() -> list[Rule]:
    """All qrkernel rules, instantiated fresh (rules keep per-run state)."""
    from .packs import KERNEL_RULES

    return [cls() for cls in KERNEL_RULES]
