"""Symbolic shape / batch-axis checks (the ``kernel-shape-mismatch`` and
``kernel-batch-axis`` analyses).

Shapes are tuples of :class:`absdom.Dim` — product normal forms over opaque
symbols — threaded through the interpreter's jnp models.  Every check fires
only on a *provable* inconsistency (two dims whose symbolic factors agree
but whose integer coefficients differ, an axis index provably outside a
known rank), so symbolic or unknown shapes can never false-positive:
``x.reshape(B, 64)`` of a ``(B, 128)`` array is flagged even though ``B`` is
unknown, while anything involving a dim the algebra cannot normalise stays
silent.
"""

from __future__ import annotations

from .absdom import Dim, IVal, format_shape, shape_product
from .interp import Event, LVal, SymVal, TVal


def _emit(interp, rule: str, mod, node, message: str) -> None:
    if mod.path in interp.check_paths:
        interp.events.append(Event(rule, mod.path, node, message))


def check_reshape(interp, src: IVal, new_dims, node, mod):
    """Element-count consistency of a reshape; returns the result shape."""
    if new_dims is None:
        return None
    holes = [i for i, d in enumerate(new_dims) if d.is_const and d.coeff == -1]
    if len(holes) > 1:
        return None
    fixed = [d for i, d in enumerate(new_dims) if i not in holes]
    if src.shape is None:
        return tuple(new_dims) if not holes else None
    old_total = shape_product(src.shape)
    new_total = shape_product(fixed)
    if holes:
        # -1 infers the hole: old_total must be divisible by the rest
        if old_total.factors == new_total.factors and new_total.coeff > 0:
            if old_total.coeff % new_total.coeff != 0:
                _emit(interp, "kernel-shape-mismatch", mod, node,
                      f"reshape of {format_shape(src.shape)} "
                      f"({old_total} elements) cannot infer -1: not divisible "
                      f"by the other dims ({new_total})")
                return None
            hole = Dim.const(old_total.coeff // new_total.coeff)
            out = list(new_dims)
            out[holes[0]] = hole
            return tuple(out)
        return None
    if old_total.provably_ne(new_total):
        _emit(interp, "kernel-shape-mismatch", mod, node,
              f"reshape of {format_shape(src.shape)} ({old_total} elements) "
              f"to {format_shape(tuple(new_dims))} ({new_total} elements): "
              "element counts provably differ")
        return None
    return tuple(new_dims)


def check_concatenate(interp, parts, axis: int, node, mod):
    shapes = [p.shape for p in parts
              if isinstance(p, IVal) and p.shape is not None]
    if len(shapes) < 2 or len(shapes) != len(parts):
        return None
    rank = len(shapes[0])
    if any(len(s) != rank for s in shapes) or not (-rank <= axis < rank):
        return None
    axis %= rank
    for i in range(rank):
        if i == axis:
            continue
        for s in shapes[1:]:
            if shapes[0][i].provably_ne(s[i]):
                _emit(interp, "kernel-shape-mismatch", mod, node,
                      f"concatenate along axis {axis}: dim {i} differs "
                      f"({shapes[0][i]} vs {s[i]}) across operands")
                return None
    out = list(shapes[0])
    if all(s[axis].is_const for s in shapes):
        out[axis] = Dim.const(sum(s[axis].coeff for s in shapes))
    else:
        out[axis] = Dim.fresh("cat")
    return tuple(out)


def _axes_list(interp, axes):
    if axes is None:
        return None
    if isinstance(axes, (TVal, LVal)):
        mode, data = interp._iter_values(axes)
        if mode != "concrete":
            return None
        out = []
        for d in data:
            if isinstance(d, IVal) and d.is_const:
                out.append(d.lo)
            else:
                return None
        return out
    if isinstance(axes, IVal) and axes.is_const:
        return [axes.lo]
    return None


def check_transpose(interp, src: IVal, axes, node, mod):
    perm = _axes_list(interp, axes)
    if perm is None:
        return tuple(reversed(src.shape)) if src.shape is not None and axes is None \
            else None
    rank = len(src.shape) if src.shape is not None else None
    norm = []
    for a in perm:
        if rank is not None and not (-rank <= a < rank):
            _emit(interp, "kernel-batch-axis", mod, node,
                  f"transpose axis {a} out of range for a rank-{rank} array "
                  f"{format_shape(src.shape)}: the batch axis this permutation "
                  "names does not exist")
            return None
        norm.append(a % rank if rank is not None else a)
    if len(set(norm)) != len(norm):
        _emit(interp, "kernel-batch-axis", mod, node,
              f"transpose permutation {perm} repeats an axis: one source axis "
              "is duplicated and another (the batch axis) is dropped")
        return None
    if rank is not None and len(norm) == rank:
        return tuple(src.shape[a] for a in norm)
    return None


def check_swapaxes(interp, src: IVal, a1, a2, node, mod):
    axes = []
    for a in (a1, a2):
        if isinstance(a, IVal) and a.is_const:
            axes.append(a.lo)
        else:
            return None
    rank = len(src.shape) if src.shape is not None else None
    if rank is None:
        return None
    for a in axes:
        if not (-rank <= a < rank):
            _emit(interp, "kernel-batch-axis", mod, node,
                  f"swapaxes axis {a} out of range for rank-{rank} array "
                  f"{format_shape(src.shape)}")
            return None
    i, j = (a % rank for a in axes)
    out = list(src.shape)
    out[i], out[j] = out[j], out[i]
    return tuple(out)


def check_matmul(interp, a: IVal, b: IVal, node, mod):
    if a.shape is None or b.shape is None or not a.shape or not b.shape:
        return None
    ka = a.shape[-1]
    kb = b.shape[0] if len(b.shape) == 1 else b.shape[-2]
    if ka.provably_ne(kb):
        _emit(interp, "kernel-shape-mismatch", mod, node,
              f"matmul contraction dims provably differ: "
              f"{format_shape(a.shape)} @ {format_shape(b.shape)} "
              f"({ka} vs {kb})")
        return None
    if len(a.shape) >= 2 and len(b.shape) >= 2:
        return (*a.shape[:-1], b.shape[-1])
    return None


def check_vmap_call(interp, vmap, args, node, mod) -> None:
    """Batch-axis bookkeeping at a ``jax.vmap(f, in_axes=…)(…)`` call."""
    in_axes = vmap.in_axes
    per_arg: list[int | None]
    axes = _axes_list(interp, in_axes) if in_axes is not None else None
    from .interp import ConstVal
    if in_axes is None:
        per_arg = [0] * len(args)
    elif isinstance(in_axes, IVal) and in_axes.is_const:
        per_arg = [in_axes.lo] * len(args)
    elif isinstance(in_axes, (TVal, LVal)):
        mode, data = interp._iter_values(in_axes)
        if mode != "concrete":
            return
        if len(data) != len(args):
            _emit(interp, "kernel-batch-axis", mod, vmap.node,
                  f"vmap in_axes names {len(data)} entries but the mapped "
                  f"function is called with {len(args)} arguments: the batch "
                  "axis of at least one operand is unaccounted for")
            return
        per_arg = []
        for d in data:
            if isinstance(d, IVal) and d.is_const:
                per_arg.append(d.lo)
            elif isinstance(d, ConstVal) and d.value is None:
                per_arg.append(None)
            else:
                per_arg.append(None)
    else:
        per_arg = [None] * len(args)
    del axes
    for i, (ax, arg) in enumerate(zip(per_arg, args)):
        if ax is None or not isinstance(arg, IVal) or arg.shape is None:
            continue
        rank = len(arg.shape)
        if not (-rank <= ax < rank):
            _emit(interp, "kernel-batch-axis", mod, node,
                  f"vmap in_axes={ax} for argument {i} is out of range for "
                  f"its rank-{rank} shape {format_shape(arg.shape)}: the "
                  "mapped batch axis does not exist (axis loss)")
