"""qrkernel CLI — ``python -m tools.analysis.kernel.run <package-or-path>``.

Exit status mirrors the qrlint/qrflow ratchet contract: 0 when the tree is
clean (modulo explicit, JUSTIFIED suppressions), 1 when any error-severity
finding remains, 2 on usage errors.  ``--format json``/``--format sarif``
emit machine-readable output; ``--proofs`` additionally reports every
``*``/``<<`` site's proof status (proved bound / wrapping / unproven) — the
facts that replaced the hand-written int32-narrowing suppressions.
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path

from ..engine import Engine, render_findings, resolve_target
from ..flow.sarif import to_sarif
from . import kernel_rules


def _resolve_target(target: str) -> Path:
    return resolve_target(target, "qrkernel")


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="qrkernel",
        description=("abstract-interpretation verifier for the JAX/Pallas "
                     "kernel layer (docs/static_analysis.md)"),
    )
    ap.add_argument("targets", nargs="*", default=["quantum_resistant_p2p_tpu"],
                    help="files, directories, or package names (default: the package)")
    ap.add_argument("--format", choices=("human", "json", "sarif"),
                    default="human", help="output format (default: human)")
    ap.add_argument("--json", action="store_true",
                    help="alias for --format json (qrlint compatibility)")
    ap.add_argument("--select", help="comma-separated rule ids to run (default: all)")
    ap.add_argument("--ignore", help="comma-separated rule ids to skip")
    ap.add_argument("--list-rules", action="store_true", help="list rules and exit")
    ap.add_argument("--proofs", action="store_true",
                    help="also report per-site interval proof status")
    args = ap.parse_args(argv)

    rules = kernel_rules()
    if args.list_rules:
        for rule in rules:
            print(f"{rule.id:28} [{rule.severity}] {rule.description}")
        return 0
    if args.select:
        wanted = {r.strip() for r in args.select.split(",")}
        unknown = wanted - {r.id for r in rules}
        if unknown:
            print(f"qrkernel: unknown rule id(s): {', '.join(sorted(unknown))}",
                  file=sys.stderr)
            return 2
        rules = [r for r in rules if r.id in wanted]
    if args.ignore:
        dropped = {r.strip() for r in args.ignore.split(",")}
        rules = [r for r in rules if r.id not in dropped]

    targets = [_resolve_target(t) for t in (args.targets or ["quantum_resistant_p2p_tpu"])]
    engine = Engine(rules)
    findings, suppressed = engine.lint_paths(targets)

    fmt = "json" if args.json else args.format
    if fmt == "sarif":
        print(json.dumps(to_sarif(findings, suppressed, rules,
                                  tool_name="qrkernel"), indent=2))
    else:
        out = render_findings(findings, suppressed, as_json=(fmt == "json"))
        if out and fmt == "human":
            lines = out.splitlines()
            lines[-1] = lines[-1].replace("qrlint:", "qrkernel:", 1)
            out = "\n".join(lines)
        if out:
            print(out)
    if args.proofs and fmt == "human":
        _print_proofs(targets)
    return 1 if any(f.severity == "error" for f in findings) else 0


def _print_proofs(targets: list[Path]) -> None:
    from ..engine import FileContext, Project
    from .packs import KernelAnalysis

    analysis = KernelAnalysis.last  # the engine run above already built it
    if analysis is None:  # e.g. --select skipped every kernel rule
        files: list[Path] = []
        for t in targets:
            files.extend(sorted(t.rglob("*.py")) if t.is_dir() else [t])
        contexts = {}
        for f in files:
            try:
                contexts[str(f)] = FileContext(str(f), f.read_text(encoding="utf-8"))
            except (SyntaxError, UnicodeDecodeError, OSError):
                continue
        analysis = KernelAnalysis.of(Project(contexts))
    proofs = analysis.proofs()
    if not proofs:
        print("qrkernel: no tile multiply/shift sites in scope")
        return
    print("qrkernel proof ledger:")
    for p in proofs:
        if p["status"] == "proved":
            print(f"  {p['path']}:{p['line']}: `{p['op']}` proved <= "
                  f"{p['bound']} ({p['bound_bits']} bits)")
        else:
            print(f"  {p['path']}:{p['line']}: `{p['op']}` {p['status']}")


if __name__ == "__main__":
    sys.exit(main())
