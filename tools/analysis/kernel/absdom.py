"""qrkernel abstract domains: integer interval + known-bits, dtypes, shapes.

The value domain is an interval ``[lo, hi]`` (``None`` = unbounded on that
side) refined with a *maybe-bits* mask — for non-negative values, the set of
bit positions that may be 1.  The mask is what makes byte-assembly proofs
exact: ``b0 | ((b1 & 0xF) << 8)`` has maybe-bits ``0xFFF``, so the OR is
known to stay a 12-bit value instead of the ``hi_a + hi_b`` a plain interval
would give.  Transfer functions compute the MATHEMATICAL result; dtype
wrapping is applied (and observed) separately by :meth:`IVal.fits`, which is
exactly the proof obligation of the value-range rule: the math interval of a
``*``/``<<`` site must fit its vector-register dtype.

Shapes are symbolic tuples of :class:`Dim` — a product normal form
``coeff * sym1 * sym2 …`` over opaque symbols (a parameter's unknown batch
dim, a sum that doesn't normalise).  Two dims are *provably different* only
when their symbolic factors agree and their integer coefficients differ;
everything else is "unknown", so symbolic code can never false-positive.
"""

from __future__ import annotations

import dataclasses
import itertools
from typing import Any, Iterable

#: integer dtypes with (lo, hi) representable ranges; floats carry no interval
INT_DTYPES: dict[str, tuple[int, int]] = {
    "bool": (0, 1),
    "uint8": (0, 2**8 - 1),
    "uint16": (0, 2**16 - 1),
    "uint32": (0, 2**32 - 1),
    "uint64": (0, 2**64 - 1),
    "int8": (-(2**7), 2**7 - 1),
    "int16": (-(2**15), 2**15 - 1),
    "int32": (-(2**31), 2**31 - 1),
    "int64": (-(2**63), 2**63 - 1),
}

FLOAT_DTYPES = ("bfloat16", "float16", "float32", "float64")

#: promotion order for the accumulator-dtype check (narrower < wider)
DTYPE_WIDTH: dict[str, int] = {
    "bool": 1, "int8": 8, "uint8": 8, "int16": 16, "uint16": 16,
    "bfloat16": 16, "float16": 16, "int32": 32, "uint32": 32, "float32": 32,
    "int64": 64, "uint64": 64, "float64": 64,
}

#: the conservative check width when a tile's dtype is unknown: TPU vector
#: registers are 32-bit and Mosaic's vector min/max are signed, so int32 is
#: the range a wrap-silent product must fit (matches qrlint's rule text)
DEFAULT_CHECK_DTYPE = "int32"

_MASK64 = 2**64 - 1


def _mask_of(hi: int) -> int:
    """Smallest all-ones mask covering ``hi`` (0 for hi <= 0)."""
    return (1 << max(hi, 0).bit_length()) - 1


@dataclasses.dataclass(frozen=True)
class IVal:
    """Abstract integer (scalar or array element): interval + maybe-bits.

    ``lo``/``hi``: inclusive bounds, ``None`` = unbounded.  ``mb``: for
    values proven non-negative, a mask of bits that may be set (``None`` =
    no bit information).  ``dtype``: the array dtype when known (host Python
    ints, which never wrap, have ``dtype=None``).  ``tile``: True when the
    value is (derived from) a kernel tile / traced array — only tile sites
    carry the 32-bit wrap hazard.
    """

    lo: int | None = None
    hi: int | None = None
    mb: int | None = None
    dtype: str | None = None
    tile: bool = False
    #: symbolic array shape (tuple of Dim) when known, None otherwise
    shape: tuple = None  # type: ignore[assignment]

    # -- constructors -------------------------------------------------------

    @staticmethod
    def const(v: int, dtype: str | None = None, tile: bool = False) -> "IVal":
        mb = v if v >= 0 else None
        return IVal(v, v, mb, dtype, tile)

    @staticmethod
    def range(lo: int | None, hi: int | None, dtype: str | None = None,
              tile: bool = False) -> "IVal":
        mb = _mask_of(hi) if (lo is not None and lo >= 0 and hi is not None) else None
        return IVal(lo, hi, mb, dtype, tile)

    @staticmethod
    def top(dtype: str | None = None, tile: bool = False) -> "IVal":
        if dtype in INT_DTYPES:
            lo, hi = INT_DTYPES[dtype]
            return IVal.range(lo, hi, dtype, tile)
        return IVal(None, None, None, dtype, tile)

    # -- queries ------------------------------------------------------------

    @property
    def is_const(self) -> bool:
        return self.lo is not None and self.lo == self.hi

    @property
    def nonneg(self) -> bool:
        return self.lo is not None and self.lo >= 0

    def effective_hi(self) -> int | None:
        """Tightest upper bound: interval hi refined by the maybe-bits mask."""
        if self.mb is not None:
            return self.mb if self.hi is None else min(self.hi, self.mb)
        return self.hi

    def fits(self, dtype: str | None) -> bool | None:
        """Does the MATH value provably fit ``dtype``'s representable range?

        True = proven in range, False = provably out of range, None = unknown.
        ``dtype=None`` checks against :data:`DEFAULT_CHECK_DTYPE` (int32).
        """
        rng = INT_DTYPES.get(dtype or DEFAULT_CHECK_DTYPE)
        if rng is None:
            return None  # float dtype: wrap analysis does not apply
        lo, hi = self.lo, self.effective_hi()
        if lo is None or hi is None:
            return None
        if rng[0] <= lo and hi <= rng[1]:
            return True
        if hi < rng[0] or lo > rng[1]:
            return False
        return None  # straddles the boundary: not provable either way

    def wrapped(self, dtype: str | None) -> "IVal":
        """The value as stored in ``dtype``: unchanged when it provably fits,
        else the full dtype range (the wrap destroyed the bound)."""
        dt = dtype if dtype is not None else self.dtype
        if dt not in INT_DTYPES:
            return dataclasses.replace(self, dtype=dt)
        if self.fits(dt) is True:
            return dataclasses.replace(self, dtype=dt)
        return IVal.top(dt, tile=self.tile)

    # -- lattice ------------------------------------------------------------

    def join(self, other: "IVal") -> "IVal":
        lo = None if self.lo is None or other.lo is None else min(self.lo, other.lo)
        hi = None if self.hi is None or other.hi is None else max(self.hi, other.hi)
        mb = None if self.mb is None or other.mb is None else (self.mb | other.mb)
        dtype = self.dtype if self.dtype == other.dtype else None
        shape = self.shape if self.shape == other.shape else None
        return IVal(lo, hi, mb, dtype, self.tile or other.tile, shape)


TOP = IVal()


def join_all(vals: Iterable[IVal]) -> IVal:
    out: IVal | None = None
    for v in vals:
        out = v if out is None else out.join(v)
    return out if out is not None else TOP


# -- transfer functions -------------------------------------------------------
#
# Each returns the MATHEMATICAL interval of the op (no dtype wrap); the
# interpreter applies .wrapped() afterwards and records the pre-wrap value at
# checked sites.  All handle unbounded operands by degrading to TOP-ish.


def _tile(a: IVal, b: IVal) -> bool:
    return a.tile or b.tile


def add(a: IVal, b: IVal) -> IVal:
    lo = None if a.lo is None or b.lo is None else a.lo + b.lo
    hi = None if a.hi is None or b.hi is None else a.hi + b.hi
    return IVal.range(lo, hi, None, _tile(a, b))


def sub(a: IVal, b: IVal) -> IVal:
    lo = None if a.lo is None or b.hi is None else a.lo - b.hi
    hi = None if a.hi is None or b.lo is None else a.hi - b.lo
    return IVal.range(lo, hi, None, _tile(a, b))


def mul(a: IVal, b: IVal) -> IVal:
    if None in (a.lo, a.hi, b.lo, b.hi):
        return IVal(None, None, None, None, _tile(a, b))
    corners = [x * y for x, y in itertools.product((a.lo, a.hi), (b.lo, b.hi))]
    return IVal.range(min(corners), max(corners), None, _tile(a, b))


def lshift(a: IVal, b: IVal) -> IVal:
    if b.lo is None or b.hi is None or b.lo < 0 or b.hi > 256:
        return IVal(None, None, None, None, _tile(a, b))
    lo = None if a.lo is None else a.lo << (b.lo if a.lo >= 0 else b.hi)
    hi = None if a.hi is None else a.hi << (b.hi if a.hi >= 0 else b.lo)
    out = IVal.range(lo, hi, None, _tile(a, b))
    if a.mb is not None and out.nonneg:
        mb = 0
        for n in range(b.lo, b.hi + 1):
            mb |= a.mb << n
        out = dataclasses.replace(out, mb=mb)
    return out


def rshift(a: IVal, b: IVal) -> IVal:
    tile = _tile(a, b)
    if b.lo is None or b.lo < 0 or not a.nonneg:
        return IVal(None, None, None, None, tile)
    if a.hi is None:  # non-negative >> non-negative stays non-negative
        return IVal(0, None, None, None, tile)
    hi = a.hi >> b.lo
    lo = 0 if b.hi is None else (a.lo >> b.hi)
    return IVal.range(lo, hi, None, tile)


def bitand(a: IVal, b: IVal) -> IVal:
    # x & mask is in [0, mask] for a non-negative mask REGARDLESS of x's sign
    # (the AND with a non-negative value clears the sign bit) — the seed fact
    # `x & 0xFF -> [0, 255]` needs no dtype knowledge.
    tile = _tile(a, b)
    mb: int | None = None
    hi: int | None = None
    for v in (a, b):
        if v.nonneg and v.hi is not None:
            m = v.mb if v.mb is not None else _mask_of(v.hi)
            mb = m if mb is None else (mb & m)
            hi = v.hi if hi is None else min(hi, v.hi)
    if mb is not None:
        return IVal(0, min(hi, mb), mb, None, tile)
    if a.nonneg or b.nonneg:  # one side non-negative, but unbounded
        return IVal(0, None, None, None, tile)
    return IVal(None, None, None, None, tile)


def bitor(a: IVal, b: IVal) -> IVal:
    if a.nonneg and b.nonneg and a.mb is not None and b.mb is not None:
        mb = a.mb | b.mb
        lo = max(a.lo, b.lo)
        return IVal(lo, mb, mb, None, _tile(a, b))
    return IVal(None, None, None, None, _tile(a, b))


def bitxor(a: IVal, b: IVal) -> IVal:
    if a.nonneg and b.nonneg and a.mb is not None and b.mb is not None:
        mb = a.mb | b.mb
        return IVal(0, mb, mb, None, _tile(a, b))
    return IVal(None, None, None, None, _tile(a, b))


def mod(a: IVal, b: IVal) -> IVal:
    # Python/jnp mod takes the divisor's sign: positive q -> [0, q-1]
    if b.lo is not None and b.lo > 0 and b.hi is not None:
        return IVal.range(0, b.hi - 1, None, _tile(a, b))
    return IVal(None, None, None, None, _tile(a, b))


def floordiv(a: IVal, b: IVal) -> IVal:
    if None in (a.lo, a.hi, b.lo, b.hi) or b.lo <= 0 <= b.hi:
        return IVal(None, None, None, None, _tile(a, b))
    corners = [x // y for x, y in itertools.product((a.lo, a.hi), (b.lo, b.hi))]
    return IVal.range(min(corners), max(corners), None, _tile(a, b))


def invert(a: IVal) -> IVal:
    lo = None if a.hi is None else -a.hi - 1
    hi = None if a.lo is None else -a.lo - 1
    return IVal.range(lo, hi, None, a.tile)


def neg(a: IVal) -> IVal:
    lo = None if a.hi is None else -a.hi
    hi = None if a.lo is None else -a.lo
    return IVal.range(lo, hi, None, a.tile)


def compare(a: IVal, b: IVal, op: str) -> IVal:
    """Abstract comparison: a bool value, concrete when decidable."""
    tile = _tile(a, b)
    if None not in (a.lo, a.hi, b.lo, b.hi):
        lt_always = a.hi < b.lo
        gt_always = a.lo > b.hi
        le_always = a.hi <= b.lo
        ge_always = a.lo >= b.hi
        table = {
            "<": (lt_always, ge_always), ">": (gt_always, le_always),
            "<=": (le_always, gt_always), ">=": (ge_always, lt_always),
            "==": (a.is_const and b.is_const and a.lo == b.lo, lt_always or gt_always),
            "!=": (lt_always or gt_always, a.is_const and b.is_const and a.lo == b.lo),
        }
        if op in table:
            true_always, false_always = table[op]
            if true_always:
                return IVal.const(1, "bool", tile)
            if false_always:
                return IVal.const(0, "bool", tile)
    return IVal.range(0, 1, "bool", tile)


# -- symbolic dims ------------------------------------------------------------

_opaque_counter = itertools.count()


@dataclasses.dataclass(frozen=True)
class Dim:
    """One symbolic array dim in product normal form: coeff * factors.

    ``factors`` is a sorted tuple of opaque symbol tokens.  A fresh opaque
    symbol is minted for anything that doesn't normalise (sums, unknown
    values), so structurally-unequal dims are merely *unknown*, never
    provably different.
    """

    coeff: int = 1
    factors: tuple[str, ...] = ()

    @staticmethod
    def const(n: int) -> "Dim":
        return Dim(n, ())

    @staticmethod
    def sym(token: str) -> "Dim":
        return Dim(1, (token,))

    @staticmethod
    def fresh(hint: str = "d") -> "Dim":
        return Dim(1, (f"{hint}?{next(_opaque_counter)}",))

    @property
    def is_const(self) -> bool:
        return not self.factors

    def __mul__(self, other: "Dim") -> "Dim":
        return Dim(self.coeff * other.coeff,
                   tuple(sorted(self.factors + other.factors)))

    def floordiv(self, n: int) -> "Dim":
        if n > 0 and self.coeff % n == 0:
            return Dim(self.coeff // n, self.factors)
        return Dim.fresh("div")

    def provably_ne(self, other: "Dim") -> bool:
        """True only when both dims share symbolic factors but differ in the
        concrete coefficient (covers fully-concrete mismatches too)."""
        return self.factors == other.factors and self.coeff != other.coeff

    def __str__(self) -> str:
        if not self.factors:
            return str(self.coeff)
        body = "*".join(f.split("?")[0] for f in self.factors)
        return body if self.coeff == 1 else f"{self.coeff}*{body}"


def shape_product(dims: Iterable[Dim]) -> Dim:
    out = Dim.const(1)
    for d in dims:
        out = out * d
    return out


def format_shape(shape: tuple[Dim, ...]) -> str:
    return "(" + ", ".join(str(d) for d in shape) + ")"


def dim_of(value: Any) -> Dim:
    """Best-effort Dim from an abstract value (IVal, SymVal, or int)."""
    if isinstance(value, Dim):
        return value
    if isinstance(value, int):
        return Dim.const(value)
    if isinstance(value, IVal) and value.is_const:
        return Dim.const(value.lo)
    inner = getattr(value, "dim", None)  # interp.SymVal (no circular import)
    if isinstance(inner, Dim):
        return inner
    return Dim.fresh()
