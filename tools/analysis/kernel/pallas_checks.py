"""Pallas structural checks (``kernel-grid-blockspec``, ``kernel-accum-dtype``).

A ``pl.pallas_call`` is evaluated into a first-class :class:`PallasVal`.  At
construction time the analyzer checks what is derivable from the call itself
(out_specs vs out_shape divisibility, index-map bounds over the concrete
grid, the kernel's stores vs the declared out dtypes); at *invocation* time
it checks the actual input arrays against the in_specs.  Everything is
gated on concreteness — symbolic grids/shapes (the live kernels' padded
batch dims) stay silent.
"""

from __future__ import annotations

from .absdom import DTYPE_WIDTH, Dim, IVal
from .interp import (TOP, BlockSpecVal, Event, FuncVal, LVal, StructVal,
                     SymVal, TVal, _Budget)


def _emit(interp, mod, node, message: str) -> None:
    if mod.path in interp.check_paths:
        interp.events.append(Event("kernel-grid-blockspec", mod.path, node,
                                   message))


def _listify(interp, v) -> list:
    if v is None:
        return []
    if isinstance(v, (LVal, TVal)):
        mode, data = interp._iter_values(v)
        return list(data) if mode == "concrete" else []
    return [v]


def _grid_dims(interp, grid) -> list[Dim] | None:
    if grid is None:
        return None
    if isinstance(grid, IVal) and grid.is_const:
        return [Dim.const(grid.lo)]
    if isinstance(grid, (TVal, LVal)):
        mode, data = interp._iter_values(grid)
        if mode != "concrete":
            return None
        out = []
        for d in data:
            if isinstance(d, IVal) and d.is_const:
                out.append(Dim.const(d.lo))
            elif isinstance(d, SymVal):
                out.append(d.dim)
            else:
                return None
        return out
    return None


def check_pallas_static(interp, pv, mod) -> None:
    grid = _grid_dims(interp, pv.grid)
    out_specs = _listify(interp, pv.out_specs)
    out_shapes = _listify(interp, pv.out_shape)
    for i, struct in enumerate(out_shapes):
        if not isinstance(struct, StructVal) or struct.shape is None:
            continue
        spec = out_specs[i] if i < len(out_specs) else None
        if isinstance(spec, BlockSpecVal):
            _check_spec(interp, mod, pv.node, spec, struct.shape, grid,
                        f"out_specs[{i}]")
    _check_kernel_stores(interp, pv, mod, out_shapes)


def check_pallas_invocation(interp, pv, args, mod):
    grid = _grid_dims(interp, pv.grid)
    in_specs = _listify(interp, pv.in_specs)
    for i, arg in enumerate(args):
        if not isinstance(arg, IVal) or arg.shape is None:
            continue
        spec = in_specs[i] if i < len(in_specs) else None
        if isinstance(spec, BlockSpecVal):
            _check_spec(interp, mod, pv.node, spec, arg.shape, grid,
                        f"in_specs[{i}]")
    out_shapes = _listify(interp, pv.out_shape)
    outs = []
    for struct in out_shapes:
        if isinstance(struct, StructVal):
            outs.append(IVal(dtype=struct.dtype, tile=True, shape=struct.shape))
        else:
            outs.append(IVal(tile=True))
    if len(outs) == 1:
        return outs[0]
    if outs:
        return TVal(tuple(outs))
    return IVal(tile=True)


def _check_spec(interp, mod, node, spec: BlockSpecVal, array_shape, grid,
                where: str) -> None:
    block = spec.block_shape
    if block is None:
        return
    if len(block) == len(array_shape):
        for i, (b, a) in enumerate(zip(block, array_shape)):
            if b.is_const and a.is_const and b.coeff > 0 \
                    and a.coeff % b.coeff != 0:
                _emit(interp, mod, node,
                      f"{where}: array dim {i} ({a}) is not divisible by the "
                      f"BlockSpec block dim ({b}): the trailing partial block "
                      "reads/writes out of bounds or pads silently")
    if spec.index_map is None or grid is None:
        return
    if not all(g.is_const for g in grid):
        return
    idx_args = [IVal.range(0, max(g.coeff - 1, 0)) for g in grid]
    try:
        result = interp._run_function(spec.index_map, tuple(idx_args))
    except _Budget:
        return
    indices = result.elems if isinstance(result, TVal) else (
        (result,) if isinstance(result, IVal) else ())
    for i, idx in enumerate(indices):
        if not isinstance(idx, IVal) or idx.hi is None or i >= len(block):
            continue
        b, a = block[i], array_shape[i] if i < len(array_shape) else None
        if a is not None and b.is_const and a.is_const:
            if (idx.hi + 1) * b.coeff > a.coeff:
                _emit(interp, mod, node,
                      f"{where}: index_map dim {i} reaches block "
                      f"{idx.hi} * {b} + {b} > array dim {a}: out-of-bounds "
                      "block under the declared grid")


def _check_kernel_stores(interp, pv, mod, out_shapes) -> None:
    """Abstractly run the kernel with out-ref dtypes seeded; a store of a
    provably wider value into a narrower out ref is a silent-narrowing
    accumulator (``kernel-accum-dtype``)."""
    kernel = pv.kernel
    if kernel is None or not isinstance(kernel, FuncVal) \
            or mod.path not in interp.check_paths:
        return
    params = interp._params(kernel.node)
    n_out = len(out_shapes)
    bound = len(kernel.bound_args)
    free = [p.arg for p in params[bound:] if p.arg not in kernel.bound_kwargs]
    out_dtypes: dict[str, str] = {}
    seeds = []
    n_in = max(len(free) - n_out, 0)
    for i, name in enumerate(free):
        if i < n_in:
            seeds.append(IVal(tile=True))
        else:
            struct = out_shapes[i - n_in]
            dt = struct.dtype if isinstance(struct, StructVal) else None
            if dt:
                out_dtypes[name] = dt
            seeds.append(IVal(dtype=dt, tile=True))

    events: list[Event] = []

    def hook(ref_name: str, value, node) -> None:
        out_dt = out_dtypes.get(ref_name)
        vdt = getattr(value, "dtype", None)
        if out_dt and vdt and DTYPE_WIDTH.get(vdt, 0) > DTYPE_WIDTH.get(out_dt, 99):
            events.append(Event(
                "kernel-accum-dtype", mod.path, node,
                f"kernel stores a {vdt} value into out ref {ref_name!r} "
                f"declared {out_dt} in out_shape: the accumulator dtype is "
                "narrower than its operands (silent truncation)"))

    key = (kernel.module.path, id(kernel.node))
    if key in interp.in_progress:
        return
    interp.in_progress.add(key)
    try:
        interp._run_function(kernel, tuple(seeds), store_hook=hook)
    except _Budget:
        return
    finally:
        interp.in_progress.discard(key)
    interp.events.extend(events)
