"""Donation-aliasing and recompile-hazard checks (pure AST, no interp).

``kernel-read-after-donate``: an operand handed to a ``donate_argnums``
position of a jitted callable is *aliased to the output buffer* — XLA may
overwrite it in place, so any later read of that name sees garbage.  The
check collects locally-visible donating callables (``f2 = jax.jit(f,
donate_argnums=(0,))`` or ``@partial(jax.jit, donate_argnums=…)``) and
flags any load of a donated argument name after the donating call and
before a rebind, statement-order within the same function.

``kernel-recompile-hazard``: a jitted function called inside a Python loop
with an argument whose SHAPE depends on the loop state — a loop-bounded
slice (``x[:i]``) or a constructor (``jnp.zeros(i)``/``arange``/``pad``)
fed a loop-derived value — compiles a fresh program every iteration: the
recompile-storm class of perf bug.  Constant shapes in loops are fine and
stay silent.
"""

from __future__ import annotations

import ast
import dataclasses


@dataclasses.dataclass
class DfEvent:
    rule: str
    node: ast.AST
    message: str


_SHAPE_CTORS = {"zeros", "ones", "full", "empty", "arange", "pad", "tile",
                "repeat", "linspace", "eye"}


def _dotted(node) -> str | None:
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _donate_positions(call: ast.Call) -> tuple[int, ...]:
    """donate_argnums of a ``jax.jit(...)``/``partial(jax.jit, ...)`` call."""
    name = _dotted(call.func) or ""
    inner_jit = name.endswith("jit")
    if name.split(".")[-1] == "partial" and call.args:
        inner = _dotted(call.args[0]) or ""
        inner_jit = inner.endswith("jit")
    if not inner_jit:
        return ()
    for kw in call.keywords:
        if kw.arg in ("donate_argnums", "donate_argnames"):
            out = []
            for sub in ast.walk(kw.value):
                if isinstance(sub, ast.Constant) and isinstance(sub.value, int):
                    out.append(sub.value)
            return tuple(out)
    return ()


def _jit_decorated(func: ast.FunctionDef) -> bool:
    for dec in func.decorator_list:
        target = dec.func if isinstance(dec, ast.Call) else dec
        name = _dotted(target) or ""
        inner = ""
        if isinstance(dec, ast.Call) and dec.args:
            inner = _dotted(dec.args[0]) or ""
        if name.endswith("jit") or inner.endswith("jit"):
            return True
    return False


def analyze_dataflow(tree: ast.Module) -> list[DfEvent]:
    events: list[DfEvent] = []
    donating: dict[str, tuple[int, ...]] = {}   # callable name -> positions
    jitted: set[str] = set()

    for stmt in ast.walk(tree):
        if isinstance(stmt, ast.FunctionDef):
            if _jit_decorated(stmt):
                jitted.add(stmt.name)
                for dec in stmt.decorator_list:
                    if isinstance(dec, ast.Call):
                        pos = _donate_positions(dec)
                        if pos:
                            donating[stmt.name] = pos
        elif isinstance(stmt, ast.Assign) and isinstance(stmt.value, ast.Call):
            pos = _donate_positions(stmt.value)
            name = (_dotted(stmt.value.func) or "").split(".")[-1]
            for tgt in stmt.targets:
                if isinstance(tgt, ast.Name):
                    if pos:
                        donating[tgt.id] = pos
                    if name == "jit" or pos:
                        jitted.add(tgt.id)

    for func in ast.walk(tree):
        if isinstance(func, ast.FunctionDef):
            events.extend(_check_read_after_donate(func, donating))
            events.extend(_check_recompile(func, jitted | set(donating)))
    return events


def _check_read_after_donate(func: ast.FunctionDef,
                             donating: dict[str, tuple[int, ...]]) -> list[DfEvent]:
    events: list[DfEvent] = []
    donated: list[tuple[str, int, str]] = []   # (var, call line, callee)
    for node in ast.walk(func):
        if isinstance(node, ast.Call) and isinstance(node.func, ast.Name) \
                and node.func.id in donating:
            for pos in donating[node.func.id]:
                if pos < len(node.args) and isinstance(node.args[pos], ast.Name):
                    donated.append((node.args[pos].id, node.lineno,
                                    node.func.id))
    if not donated:
        return events
    rebinds: dict[str, list[int]] = {}
    for node in ast.walk(func):
        targets = []
        if isinstance(node, ast.Assign):
            targets = node.targets
        elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
            targets = [node.target]
        elif isinstance(node, ast.For):
            targets = [node.target]
        for tgt in targets:
            for sub in ast.walk(tgt):
                if isinstance(sub, ast.Name):
                    rebinds.setdefault(sub.id, []).append(node.lineno)
    seen: set[tuple[str, int]] = set()
    for node in ast.walk(func):
        if not (isinstance(node, ast.Name) and isinstance(node.ctx, ast.Load)):
            continue
        for var, call_line, callee in donated:
            if node.id != var or node.lineno <= call_line:
                continue
            # a rebind ON the call line is the canonical donation pattern
            # (`state = step(state, x)`): the store happens after the call
            rebound = any(call_line <= r <= node.lineno
                          for r in rebinds.get(var, ()))
            key = (var, node.lineno)
            if not rebound and key not in seen:
                seen.add(key)
                events.append(DfEvent(
                    "kernel-read-after-donate", node,
                    f"{var!r} is read after being donated to {callee}() on "
                    f"line {call_line}: donate_argnums aliases the operand "
                    "to the output buffer, so this read sees overwritten "
                    "memory"))
    return events


def _loop_tainted_names(loop: ast.For) -> set[str]:
    names = {n.id for n in ast.walk(loop.target) if isinstance(n, ast.Name)}
    grew = True
    while grew:
        grew = False
        for node in ast.walk(loop):
            if isinstance(node, ast.Assign):
                refs = {n.id for n in ast.walk(node.value)
                        if isinstance(n, ast.Name) and isinstance(n.ctx, ast.Load)}
                if refs & names:
                    for tgt in node.targets:
                        for sub in ast.walk(tgt):
                            if isinstance(sub, ast.Name) and sub.id not in names:
                                names.add(sub.id)
                                grew = True
    return names


def _check_recompile(func: ast.FunctionDef, jitted: set[str]) -> list[DfEvent]:
    events: list[DfEvent] = []
    if not jitted:
        return events
    for loop in ast.walk(func):
        if not isinstance(loop, ast.For):
            continue
        tainted = _loop_tainted_names(loop)
        for call in ast.walk(loop):
            if not (isinstance(call, ast.Call) and isinstance(call.func, ast.Name)
                    and call.func.id in jitted):
                continue
            for arg in call.args:
                hazard = _shape_depends_on(arg, tainted)
                if hazard is not None:
                    events.append(DfEvent(
                        "kernel-recompile-hazard", call,
                        f"{call.func.id}() is jitted but called in a loop "
                        f"with an argument whose shape depends on loop "
                        f"state ({hazard}): every iteration traces and "
                        "compiles a fresh program (recompile storm); pad to "
                        "a fixed shape or lift the call out of the loop"))
                    break
    return events


def _shape_depends_on(arg: ast.AST, tainted: set[str]) -> str | None:
    """A description of the loop-dependent shape expression, or None."""

    def refs_tainted(node) -> bool:
        return any(isinstance(n, ast.Name) and n.id in tainted
                   for n in ast.walk(node))

    for node in ast.walk(arg):
        if isinstance(node, ast.Subscript) and isinstance(node.slice, ast.Slice):
            for bound in (node.slice.lower, node.slice.upper):
                if bound is not None and refs_tainted(bound):
                    return "a loop-bounded slice"
        elif isinstance(node, ast.Call):
            name = (_dotted(node.func) or "").split(".")[-1]
            if name in _SHAPE_CTORS:
                shape_args = list(node.args[:2]) + [
                    kw.value for kw in node.keywords
                    if kw.arg in ("shape", "pad_width", "reps", "repeats")]
                if any(refs_tainted(a) for a in shape_args):
                    return f"a loop-derived {name}() shape"
    return None
