"""Asyncio-discipline rule pack.

The networking stack (net/, app/) serves every peer from one event loop;
the ML-KEM TLS literature (arxiv 2404.13544, PAPERS.md) shows handshake
stacks live or die on exception/timeout discipline.  Four failure modes:

* ``dangling-task`` — ``asyncio.create_task``/``ensure_future`` whose result
  is discarded: the task can be garbage-collected mid-flight and its
  exception is silently dropped at interpreter exit.
* ``unawaited-coroutine`` — calling a coroutine function defined in the same
  module without awaiting it: the body never runs (RuntimeWarning at GC).
* ``blocking-in-async`` — ``time.sleep``/``getpass``/sync file I/O directly
  inside ``async def``: stalls every connected peer for the duration (the
  event loop is shared).  ``FileLock.acquire`` is on the blocklist because
  its retry loop sleeps (storage/secure_file.py documents it as sync-only;
  use ``acquire_async`` from coroutines).
* ``broad-except`` — ``except Exception``/bare ``except`` whose handler
  neither logs, re-raises, nor forwards the error to a future: failures
  vanish.  Bare ``except`` additionally swallows ``CancelledError``, wedging
  task cancellation.
"""

from __future__ import annotations

import ast

from .engine import FileContext, Rule, call_name

_TASK_SPAWNERS = {"create_task", "ensure_future"}

#: dotted call names that block the event loop when called from async code
_BLOCKING_CALLS = {
    "time.sleep": "use `await asyncio.sleep(...)`",
    "getpass.getpass": "run it in an executor: `await loop.run_in_executor(None, getpass.getpass, prompt)`",
    "input": "read through the asyncio stream reader or an executor",
    "open": "wrap the I/O in `loop.run_in_executor`",
    "subprocess.run": "use `asyncio.create_subprocess_exec`",
    "subprocess.check_output": "use `asyncio.create_subprocess_exec`",
    "socket.create_connection": "use `asyncio.open_connection`",
}
#: method names that are sync file I/O or documented-sync locks regardless of
#: receiver (Path.read_bytes(...), FileLock.acquire(), ...)
_BLOCKING_METHODS = {
    "read_bytes": "sync file I/O",
    "write_bytes": "sync file I/O",
    "read_text": "sync file I/O",
    "write_text": "sync file I/O",
}
#: attribute calls blocking only for specific receivers — FileLock.acquire is
#: sync-only by contract (storage/secure_file.py); asyncio primitives named
#: `acquire` (Lock, Semaphore) are awaited, so a bare `.acquire()` expression
#: statement inside async code is wrong either way.
_SYNC_ONLY_METHODS = {"acquire": "FileLock.acquire is sync-only; await acquire_async() instead"}

_LOGGING_METHODS = {"debug", "info", "warning", "error", "exception", "critical",
                    "log", "log_event", "print"}


def _async_def_names(tree: ast.Module) -> set[str]:
    return {n.name for n in ast.walk(tree) if isinstance(n, ast.AsyncFunctionDef)}


def _in_async_function(ctx: FileContext) -> bool:
    func = ctx.enclosing(ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
    return isinstance(func, ast.AsyncFunctionDef)


class DanglingTaskRule(Rule):
    id = "dangling-task"
    description = (
        "create_task/ensure_future result discarded: task may be GC'd "
        "mid-flight and its exception silently dropped"
    )

    def start_file(self, ctx: FileContext):
        return {ast.Expr: lambda n: self._check(ctx, n)}

    def _check(self, ctx: FileContext, node: ast.Expr) -> None:
        call = node.value
        if isinstance(call, ast.Await):
            return
        if not isinstance(call, ast.Call):
            return
        name = call_name(call) or ""
        if name.split(".")[-1] in _TASK_SPAWNERS:
            ctx.report(
                self, call,
                f"result of {name}() discarded: keep a strong reference and "
                "attach a done-callback that logs unexpected exceptions",
            )


class UnawaitedCoroutineRule(Rule):
    id = "unawaited-coroutine"
    description = "coroutine called without await: its body never runs"

    def start_file(self, ctx: FileContext):
        self._async_names = _async_def_names(ctx.tree)
        if not self._async_names:
            return None
        return {ast.Expr: lambda n: self._check(ctx, n)}

    def _check(self, ctx: FileContext, node: ast.Expr) -> None:
        call = node.value
        if not isinstance(call, ast.Call):
            return
        # only bare names and self/cls methods: `asyncio.run(run())` must not
        # collide with a local coroutine that happens to be called `run`
        func = call.func
        if isinstance(func, ast.Name):
            name = func.id
        elif (isinstance(func, ast.Attribute) and isinstance(func.value, ast.Name)
              and func.value.id in ("self", "cls")):
            name = func.attr
        else:
            return
        if name in self._async_names:
            ctx.report(
                self, call,
                f"coroutine {name}() is never awaited (its body will not run); "
                "await it or schedule it as a supervised task",
            )


class BlockingInAsyncRule(Rule):
    id = "blocking-in-async"
    description = "blocking call inside async def stalls the shared event loop"

    def start_file(self, ctx: FileContext):
        return {ast.Call: lambda n: self._check(ctx, n)}

    def _check(self, ctx: FileContext, node: ast.Call) -> None:
        if not _in_async_function(ctx):
            return
        name = call_name(node) or ""
        if name in _BLOCKING_CALLS:
            ctx.report(self, node,
                       f"blocking {name}() inside async def; {_BLOCKING_CALLS[name]}")
            return
        if isinstance(node.func, ast.Attribute):
            attr = node.func.attr
            if attr in _BLOCKING_METHODS:
                ctx.report(
                    self, node,
                    f"{_BLOCKING_METHODS[attr]} (.{attr}()) inside async def; "
                    "wrap it in `loop.run_in_executor`",
                )
            elif attr in _SYNC_ONLY_METHODS and self._is_bare_expr(ctx, node):
                ctx.report(self, node, _SYNC_ONLY_METHODS[attr])

    @staticmethod
    def _is_bare_expr(ctx: FileContext, node: ast.Call) -> bool:
        stmt = ctx.enclosing_statement(node)
        return isinstance(stmt, ast.Expr) and stmt.value is node


class BroadExceptRule(Rule):
    id = "broad-except"
    description = (
        "broad except that neither logs, re-raises, nor forwards the error; "
        "bare except additionally swallows CancelledError"
    )

    def start_file(self, ctx: FileContext):
        return {ast.ExceptHandler: lambda n: self._check(ctx, n)}

    def _check(self, ctx: FileContext, node: ast.ExceptHandler) -> None:
        kind = self._broad_kind(node.type)
        if kind is None:
            return
        if kind in ("bare", "BaseException"):
            if not self._reraises(node):
                ctx.report(
                    self, node,
                    f"{'bare except' if kind == 'bare' else 'except BaseException'} "
                    "swallows CancelledError/KeyboardInterrupt; catch Exception "
                    "or re-raise",
                )
            return
        if not self._handles(node):
            ctx.report(
                self, node,
                "except Exception with no logging/re-raise/set_exception: "
                "failures vanish silently; narrow the except, log the error, "
                "or annotate why silence is the contract",
            )

    @staticmethod
    def _broad_kind(type_node: ast.AST | None) -> str | None:
        if type_node is None:
            return "bare"
        names = []
        if isinstance(type_node, ast.Tuple):
            names = [getattr(e, "id", getattr(e, "attr", "")) for e in type_node.elts]
        else:
            names = [getattr(type_node, "id", getattr(type_node, "attr", ""))]
        if "BaseException" in names:
            return "BaseException"
        if "Exception" in names:
            return "Exception"
        return None

    @staticmethod
    def _reraises(node: ast.ExceptHandler) -> bool:
        return any(isinstance(n, ast.Raise) for n in ast.walk(node))

    @staticmethod
    def _handles(node: ast.ExceptHandler) -> bool:
        for n in ast.walk(node):
            if isinstance(n, ast.Raise):
                return True
            if isinstance(n, ast.Call):
                # attr check (not dotted-name) so chained receivers like
                # logging.getLogger(__name__).exception(...) count
                if isinstance(n.func, ast.Attribute) and (
                    n.func.attr in _LOGGING_METHODS or n.func.attr == "set_exception"
                ):
                    return True
                name = (call_name(n) or "").split(".")[-1]
                if name in _LOGGING_METHODS:
                    return True
        return False


ASYNCIO_RULES = (DanglingTaskRule, UnawaitedCoroutineRule,
                 BlockingInAsyncRule, BroadExceptRule)
