"""Provider-contract rule pack (cross-file) + dispatch/breaker discipline.

The registry (provider/registry.py) is the only seam between the protocol
engine and the crypto backends: ``SecureMessaging`` calls whatever the
factory returns through the ``provider/base.py`` surface, and the batching
queue (provider/batched.py) additionally requires the ``*_batch`` methods to
accept the exact positional shape it forwards.  A registered class missing a
method, or overriding a batch method with renamed/reordered parameters, only
fails at runtime — mid-handshake.  This rule proves the contract statically:

* every class reachable from a ``register_kem``/``register_signature``/
  ``register_fused`` call (or listed in the AEAD table) implements each
  ``@abc.abstractmethod`` of its base-interface, directly or via a project
  base class — for ``register_fused`` that interface is the optional
  composite-op capability surface (``FusedHandshakeOps``), so a fused
  provider whose batch programs drift from the capability contract fails
  the lint, not a live handshake;
* every override of a base-class method keeps the base's positional
  parameter names in order (extra trailing parameters must have defaults).
"""

from __future__ import annotations

import ast

from .engine import FileContext, Project, Rule, call_name, dotted_name, last_attr

_BASE_SUFFIX = "provider/base.py"
_REGISTRY_SUFFIX = "provider/registry.py"

#: interface -> the registry call that binds implementations to it
_INTERFACES = {
    "KeyExchangeAlgorithm": "register_kem",
    "SignatureAlgorithm": "register_signature",
    "SymmetricAlgorithm": "_AEADS",
    "FusedHandshakeOps": "register_fused",
}


def _method_params(func: ast.FunctionDef) -> list[str]:
    """Positional parameter names (without self) + set of defaulted names."""
    args = func.args
    return [a.arg for a in [*args.posonlyargs, *args.args] if a.arg != "self"]


def _defaulted_params(func: ast.FunctionDef) -> set[str]:
    args = func.args
    pos = [a.arg for a in [*args.posonlyargs, *args.args]]
    out = set(pos[len(pos) - len(args.defaults):]) if args.defaults else set()
    out.update(a.arg for a, d in zip(args.kwonlyargs, args.kw_defaults) if d is not None)
    return out


def _is_abstract(func: ast.FunctionDef) -> bool:
    for dec in func.decorator_list:
        name = call_name(dec) if isinstance(dec, ast.Call) else None
        name = name or (dec.attr if isinstance(dec, ast.Attribute) else
                        dec.id if isinstance(dec, ast.Name) else None)
        if name and "abstractmethod" in name:
            return True
    return False


class _ClassIndex:
    """All class defs in the project, by name, with base-name edges."""

    def __init__(self, project: Project):
        self.classes: dict[str, tuple[ast.ClassDef, object]] = {}
        for ctx in project.contexts.values():
            for node in ast.walk(ctx.tree):
                if isinstance(node, ast.ClassDef):
                    # last definition wins; names are unique in this package
                    self.classes[node.name] = (node, ctx)

    def mro_methods(self, name: str) -> dict[str, ast.FunctionDef]:
        """Methods visible on ``name``: own methods shadow base methods."""
        out: dict[str, ast.FunctionDef] = {}
        seen: set[str] = set()

        def collect(cls_name: str) -> None:
            if cls_name in seen or cls_name not in self.classes:
                return
            seen.add(cls_name)
            cls, _ctx = self.classes[cls_name]
            for item in cls.body:
                if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    out.setdefault(item.name, item)
            for base in cls.bases:
                base_name = base.id if isinstance(base, ast.Name) else (
                    base.attr if isinstance(base, ast.Attribute) else None)
                if base_name:
                    collect(base_name)

        collect(name)
        return out


class ProviderContractRule(Rule):
    id = "provider-contract"
    description = (
        "registered algorithm must implement the full provider/base.py "
        "surface with matching batch-method signatures"
    )

    def check_project(self, project: Project) -> None:
        base_ctx = project.find_file(_BASE_SUFFIX)
        registry_ctx = project.find_file(_REGISTRY_SUFFIX)
        if base_ctx is None or registry_ctx is None:
            return  # not linting the provider layer in this run
        index = _ClassIndex(project)
        contracts = self._interface_contracts(base_ctx)
        for cls_name, interface in self._registered_classes(registry_ctx, index):
            contract = contracts.get(interface)
            if contract is None:
                continue
            self._check_class(project, index, cls_name, interface, contract)

    # -- contract extraction ------------------------------------------------

    def _interface_contracts(self, base_ctx) -> dict[str, dict]:
        """interface name -> {"abstract": {name}, "signatures": {name: params}}."""
        out: dict[str, dict] = {}
        for node in ast.walk(base_ctx.tree):
            if not isinstance(node, ast.ClassDef) or node.name not in _INTERFACES:
                continue
            abstract: set[str] = set()
            signatures: dict[str, list[str]] = {}
            for item in node.body:
                if not isinstance(item, ast.FunctionDef):
                    continue
                if _is_abstract(item):
                    abstract.add(item.name)
                if not item.name.startswith("__"):
                    signatures[item.name] = _method_params(item)
            out[node.name] = {"abstract": abstract, "signatures": signatures}
        return out

    def _registered_classes(self, registry_ctx, index: _ClassIndex):
        """Yield (class_name, interface_name) for every registration site."""
        seen: set[str] = set()
        for node in ast.walk(registry_ctx.tree):
            # register_kem("name", lambda ...: ClassName(...), backends)
            if isinstance(node, ast.Call):
                fname = (call_name(node) or "").split(".")[-1]
                interface = {v: k for k, v in _INTERFACES.items()}.get(fname)
                if interface is None:
                    continue
                for cls_name in self._called_classes(node):
                    if cls_name not in seen:
                        seen.add(cls_name)
                        yield cls_name, interface
            # _AEADS = {"AES-256-GCM": AES256GCM, ...} (plain or annotated)
            elif isinstance(node, (ast.Assign, ast.AnnAssign)):
                if isinstance(node, ast.Assign):
                    targets = [getattr(t, "id", None) for t in node.targets]
                else:
                    targets = [getattr(node.target, "id", None)]
                if "_AEADS" in targets and isinstance(node.value, ast.Dict):
                    for v in node.value.values:
                        if isinstance(v, ast.Name) and v.id not in seen:
                            seen.add(v.id)
                            yield v.id, "SymmetricAlgorithm"

    @staticmethod
    def _called_classes(call: ast.Call):
        """CapitalizedName(...) calls inside a registration's factory arg."""
        for node in ast.walk(call):
            if isinstance(node, ast.Call) and isinstance(node.func, ast.Name):
                if node.func.id[:1].isupper():
                    yield node.func.id

    # -- checking -----------------------------------------------------------

    def _check_class(self, project: Project, index: _ClassIndex, cls_name: str,
                     interface: str, contract: dict) -> None:
        if cls_name not in index.classes:
            return  # defined outside the linted tree
        cls, ctx = index.classes[cls_name]
        methods = index.mro_methods(cls_name)
        for name in sorted(contract["abstract"]):
            impl = methods.get(name)
            if impl is None or _is_abstract(impl):
                project.report(
                    self, ctx, cls,
                    f"{cls_name} is registered as a {interface} but does not "
                    f"implement abstract method {name}()",
                )
        for name, base_params in contract["signatures"].items():
            impl = methods.get(name)
            if impl is None or _is_abstract(impl):
                continue
            impl_params = _method_params(impl)
            if impl_params[: len(base_params)] != base_params:
                project.report(
                    self, ctx, impl,
                    f"{cls_name}.{name}({', '.join(impl_params)}) does not "
                    f"match the {interface} signature ({', '.join(base_params)}): "
                    "the batch queue forwards these positionally",
                )
                continue
            extra = impl_params[len(base_params):]
            defaulted = _defaulted_params(impl)
            bad = [p for p in extra if p not in defaulted]
            if bad:
                project.report(
                    self, ctx, impl,
                    f"{cls_name}.{name} adds required parameter(s) "
                    f"{', '.join(bad)} beyond the {interface} surface; give "
                    "them defaults so interface callers keep working",
                )


class DispatchExceptBreakerRule(Rule):
    """The round-3 regression class: a device dispatch wrapped in an
    ``except`` that swallows the failure WITHOUT recording it to the circuit
    breaker leaves the degrade/heal machinery blind — the fleet silently
    stays (or silently goes) degraded.  Any ``try`` whose body performs a
    device dispatch (a ``batch_fn(...)`` call, or ``run_in_executor`` given
    the breaker's device/warm-up executor or a ``batch_fn`` callable) must
    have every broad/``Exception``/``TimeoutError`` handler either re-raise
    or RECORD THE FAILURE to the breaker (``trip`` / ``record_failure`` /
    ``quarantine`` / a ``*trip_breaker*`` helper).  ``release`` and
    ``record_success`` deliberately do NOT count: releasing a claim records
    no outcome and the success path is exactly what a swallowed failure
    must not take.

    The sharded crypto plane (provider/scheduler.py) extends the dispatch
    surface: ``run_placed(...)`` executes a device program under a shard's
    placement context, and the per-SHARD breakers it routes outcomes to
    use the same recording names — so a swallowed placed-dispatch failure
    on one shard is caught exactly like a single-breaker one.

    The gateway fleet (fleet/manager.py) extends it again at the second
    placement level: ``_probe_call(...)`` is the fleet breaker's half-open
    canary dispatch (one control round-trip to a maybe-dead gateway), and
    a swallowed probe failure would leave that member's breaker half-open
    forever — the fleet-scope twin of a swallowed device canary.
    """

    id = "dispatch-except-no-breaker"
    description = (
        "except around a device dispatch neither re-raises nor records the "
        "failure to the circuit breaker (trip/record_failure/_trip_breaker)"
    )

    #: called-function names that ARE a device dispatch (run_placed is the
    #: scheduler's placement boundary: one placed device program;
    #: _probe_call is the fleet router's half-open canary dispatch)
    _DISPATCH_CALLEES = {"batch_fn", "_device_call", "_warm_call",
                         "run_placed", "_probe_call"}
    #: executor attributes whose run_in_executor submissions are dispatches
    _DISPATCH_EXECUTORS = {"device_executor", "warmup_executor"}
    #: handler calls that count as recording the FAILURE to the breaker
    #: (release/record_success do not: no outcome / the success path)
    _BREAKER_CALLS = {"trip", "record_failure", "quarantine"}

    def start_file(self, ctx: FileContext):
        return {ast.Try: lambda n: self._check(ctx, n)}

    def _is_dispatch_call(self, call: ast.Call) -> bool:
        name = last_attr(call.func)
        if name in self._DISPATCH_CALLEES:
            return True
        if name == "run_in_executor":
            for arg in call.args:
                dotted = dotted_name(arg) or ""
                leaf = dotted.rsplit(".", 1)[-1]
                if (leaf in self._DISPATCH_CALLEES
                        or leaf in self._DISPATCH_EXECUTORS):
                    return True
        return False

    def _body_dispatches(self, try_node: ast.Try) -> bool:
        for stmt in try_node.body:
            for node in ast.walk(stmt):
                if isinstance(node, ast.Call) and self._is_dispatch_call(node):
                    return True
        return False

    @staticmethod
    def _is_broad(handler: ast.ExceptHandler) -> bool:
        if handler.type is None:
            return True  # bare except
        types = (handler.type.elts if isinstance(handler.type, ast.Tuple)
                 else [handler.type])
        for t in types:
            name = last_attr(t) or ""
            if name in ("Exception", "BaseException", "TimeoutError"):
                return True
        return False

    def _handler_records(self, handler: ast.ExceptHandler) -> bool:
        for node in ast.walk(handler):
            if isinstance(node, ast.Raise):
                return True
            if isinstance(node, ast.Call):
                name = last_attr(node.func) or ""
                if name in self._BREAKER_CALLS or "trip_breaker" in name:
                    return True
        return False

    def _check(self, ctx: FileContext, node: ast.Try) -> None:
        if not self._body_dispatches(node):
            return
        for handler in node.handlers:
            if self._is_broad(handler) and not self._handler_records(handler):
                ctx.report(
                    self, handler,
                    "except around a device dispatch swallows the failure "
                    "without recording it to the circuit breaker; call "
                    "breaker.record_failure()/trip() (or a *_trip_breaker "
                    "helper) or re-raise so degradation stays visible and "
                    "healable",
                )


PROVIDER_RULES = (ProviderContractRule, DispatchExceptBreakerRule)
