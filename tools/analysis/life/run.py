"""qrlife CLI — ``python -m tools.analysis.life.run <package-or-path>``.

Exit status mirrors the qrlint/qrflow/qrkernel/qrproto ratchet contract:
0 when the tree is clean (modulo explicit, JUSTIFIED suppressions), 1
when any error-severity finding remains, 2 on usage errors.
``--format json``/``--format sarif`` emit machine-readable output;
``--dump-lock-graph`` prints the project lock-order graph (one
``src -> dst  site`` line per edge) instead of linting — the quickest
way to see why a ``life-lock-cycle`` finding names the locks it does.
"""

from __future__ import annotations

import json
import sys
import argparse

from ..engine import Engine, render_findings, resolve_target
from ..flow.sarif import to_sarif
from . import life_rules


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="qrlife",
        description=("lock-discipline & resource-lifetime verifier for the "
                     "multi-process fleet (docs/static_analysis.md)"),
    )
    ap.add_argument("targets", nargs="*", default=["quantum_resistant_p2p_tpu"],
                    help="files, directories, or package names (default: the package)")
    ap.add_argument("--format", choices=("human", "json", "sarif"),
                    default="human", help="output format (default: human)")
    ap.add_argument("--json", action="store_true",
                    help="alias for --format json (qrlint compatibility)")
    ap.add_argument("--select", help="comma-separated rule ids to run (default: all)")
    ap.add_argument("--ignore", help="comma-separated rule ids to skip")
    ap.add_argument("--list-rules", action="store_true", help="list rules and exit")
    ap.add_argument("--dump-lock-graph", action="store_true",
                    help="print the lock-order graph edges and exit")
    args = ap.parse_args(argv)

    rules = life_rules()
    if args.list_rules:
        for rule in rules:
            print(f"{rule.id:30} [{rule.severity}] {rule.description}")
        return 0
    if args.select:
        wanted = {r.strip() for r in args.select.split(",")}
        unknown = wanted - {r.id for r in rules}
        if unknown:
            print(f"qrlife: unknown rule id(s): {', '.join(sorted(unknown))}",
                  file=sys.stderr)
            return 2
        rules = [r for r in rules if r.id in wanted]
    if args.ignore:
        dropped = {r.strip() for r in args.ignore.split(",")}
        rules = [r for r in rules if r.id not in dropped]

    targets = [resolve_target(t, "qrlife")
               for t in (args.targets or ["quantum_resistant_p2p_tpu"])]
    fmt = "json" if args.json else args.format

    if args.dump_lock_graph:
        from ..engine import FileContext, Project
        from .packs import LifeAnalysis
        contexts = {}
        for t in targets:
            files = sorted(t.rglob("*.py")) if t.is_dir() else [t]
            for f in files:
                try:
                    contexts[str(f)] = FileContext(str(f), f.read_text(encoding="utf-8"))
                except (SyntaxError, UnicodeDecodeError, OSError):
                    continue
        analysis = LifeAnalysis.of(Project(contexts))
        for e in sorted(analysis.locks.edges,
                        key=lambda e: (e.src, e.dst, e.fn.path)):
            site = f"{e.fn.path}:{getattr(e.node, 'lineno', '?')}"
            via = f"  (via {e.via})" if e.via else ""
            print(f"{e.src} -> {e.dst}  {site} in {e.fn.qualname}{via}")
        return 0

    engine = Engine(rules)
    findings, suppressed = engine.lint_paths(targets)

    if fmt == "sarif":
        print(json.dumps(to_sarif(findings, suppressed, rules,
                                  tool_name="qrlife"), indent=2))
    else:
        out = render_findings(findings, suppressed, as_json=(fmt == "json"))
        if out and fmt == "human":
            lines = out.splitlines()
            lines[-1] = lines[-1].replace("qrlint:", "qrlife:", 1)
            out = "\n".join(lines)
        if out:
            print(out)
    return 1 if any(f.severity == "error" for f in findings) else 0


if __name__ == "__main__":
    sys.exit(main())
