"""qrlife analysis packs, exposed as qrlint ``Rule`` objects.

One :class:`LifeAnalysis` is computed per project run (call graph ->
lock registry/order graph -> resource path scan -> wipe-completeness
walk) and cached on the ``Project``; the thin rule classes below each
publish their own finding id from it, so ``--select``/``--ignore`` and
the inline ``# qrlife: disable=`` suppression machinery work unchanged.

Rule ids:

=========================  ================================================
life-lock-cycle            cycle in the project lock-acquisition order
                           graph (potential deadlock)
life-await-under-lock      threading lock held across an ``await`` or a
                           blocking call in event-loop code
life-unreleased-lock       bare ``acquire()`` whose release an exception
                           path can skip
life-leak-on-raise         resource acquisition (subprocess, socket/
                           StreamWriter, executor, telemetry server,
                           tempdir, task) whose release is not proven on
                           exception edges
life-double-release        the same release verb on the same receiver
                           twice, unconditionally, in one block
life-wipe-gap              a SECRET-source local misses _wipe()/zeroize()
                           on an explicit exit path
life-unjustified-suppression  a qrlife suppression with no justification
=========================  ================================================
"""

from __future__ import annotations

import re

from ..engine import FileContext, Project, Rule
from ..flow.domains import infer_domains
from .callgraph_shim import build_callgraph
from .locks import LockAnalysis
from .resources import run_resources
from .wipes import run_wipes

# every prefix: the engine accepts `# qrlint: disable=…` (and the other
# analyzers' spellings) too, so a life rule suppressed through THOSE
# prefixes must be policed all the same
_SUPPRESS_RE = re.compile(
    r"#\s*(?:qrlint|qrkernel|qrproto|qrlife):\s*disable(?:-file)?\s*=\s*"
    r"(?P<rules>[\w.,\- ]+)(?P<rest>.*)$")


class LifeAnalysis:
    """All qrlife findings for one project, computed once and cached."""

    def __init__(self, project: Project):
        self.project = project
        self.cg = build_callgraph(project)
        self.domains = infer_domains(self.cg)
        self.findings: list[tuple[str, FileContext, object, str]] = []
        self._run_locks()
        self._run_resources()
        self._run_wipes()

    @classmethod
    def of(cls, project: Project) -> "LifeAnalysis":
        cached = getattr(project, "_qrlife_analysis", None)
        if cached is None:
            cached = cls(project)
            project._qrlife_analysis = cached  # type: ignore[attr-defined]
        return cached

    def _add(self, rule_id: str, ctx: FileContext, node, message: str) -> None:
        self.findings.append((rule_id, ctx, node, message))

    def _run_locks(self) -> None:
        locks = LockAnalysis(self.cg, self.domains)
        self.locks = locks
        for cyc in locks.cycles():
            rep = min(cyc, key=lambda e: (e.fn.path, getattr(e.node, "lineno", 0)))
            parts = [cyc[0].src]
            for e in cyc:
                parts.append(
                    f"{e.dst} ({e.fn.qualname}"
                    f"{' via ' + e.via if e.via else ''})")
            path = " -> ".join(parts)
            self._add(
                "life-lock-cycle", rep.fn.ctx, rep.node,
                f"lock-order cycle (potential deadlock): {path}; pick one "
                "global acquisition order and release before crossing it")
        seen: set[tuple[str, str, int]] = set()
        for hz in locks.hazards:
            key = (hz.rule, hz.fn.path, getattr(hz.node, "lineno", 0))
            if key in seen:
                continue
            seen.add(key)
            self._add(hz.rule, hz.fn.ctx, hz.node,
                      f"{hz.message} [in {hz.fn.qualname}]")

    def _run_resources(self) -> None:
        seen: set[tuple[str, str, int]] = set()
        for leak in run_resources(self.cg):
            key = (leak.rule, leak.fn.path, getattr(leak.node, "lineno", 0))
            if key in seen:
                continue
            seen.add(key)
            self._add(leak.rule, leak.fn.ctx, leak.node,
                      f"{leak.message} [in {leak.fn.qualname}]")

    def _run_wipes(self) -> None:
        seen: set[tuple[str, int]] = set()
        for gap in run_wipes(self.cg):
            key = (gap.fn.path, getattr(gap.node, "lineno", 0))
            if key in seen:
                continue
            seen.add(key)
            self._add("life-wipe-gap", gap.fn.ctx, gap.node, gap.message)


class _LifeRule(Rule):
    """Base: publish one finding id out of the shared analysis."""

    severity = "error"

    def check_project(self, project: Project) -> None:
        analysis = LifeAnalysis.of(project)
        for rule_id, ctx, node, message in analysis.findings:
            if rule_id == self.id:
                project.report(self, ctx, node, message)


class LockCycleRule(_LifeRule):
    id = "life-lock-cycle"
    description = ("cycle in the project-wide lock-acquisition order graph "
                   "(interprocedural, via the qrflow call graph) — a "
                   "potential deadlock between two execution contexts")


class AwaitUnderLockRule(_LifeRule):
    id = "life-await-under-lock"
    description = ("threading lock held across an await or a blocking call "
                   "(time.sleep / socket ops) in event-loop code: every "
                   "contending thread stalls for the whole suspension")


class UnreleasedLockRule(_LifeRule):
    id = "life-unreleased-lock"
    description = ("bare acquire() whose matching release() an exception "
                   "path can skip — use `with` or move release into finally")


class LeakOnRaiseRule(_LifeRule):
    id = "life-leak-on-raise"
    description = ("resource acquisition (subprocess / socket / StreamWriter "
                   "/ executor / telemetry server / tempdir / task) whose "
                   "release is not postdominated by exception edges: finally, "
                   "context manager, done-callback, or ownership transfer "
                   "are the accepted proofs")


class DoubleReleaseRule(_LifeRule):
    id = "life-double-release"
    description = ("same release verb on the same receiver twice, "
                   "unconditionally, in one straight-line block")


class WipeGapRule(_LifeRule):
    id = "life-wipe-gap"
    description = ("a local bound from a SECRET taint source (qrflow's "
                   "lattice) misses _wipe()/zeroize() on an explicit exit "
                   "path and never escapes ownership")


class UnjustifiedLifeSuppressionRule(Rule):
    """Suppressing a qrlife finding requires a one-line justification after
    the rule ids — the same convention every other analyzer enforces."""

    id = "life-unjustified-suppression"
    severity = "error"
    description = ("a qrlife suppression comment carries no one-line "
                   "justification after the rule id(s)")

    _POLICED: frozenset[str] = frozenset({
        "life-lock-cycle", "life-await-under-lock", "life-unreleased-lock",
        "life-leak-on-raise", "life-double-release", "life-wipe-gap",
        "life-unjustified-suppression",
    })

    def check_project(self, project: Project) -> None:
        for ctx in project.contexts.values():
            for lineno, line in enumerate(ctx.lines, start=1):
                m = _SUPPRESS_RE.search(line)
                if not m:
                    continue
                blob = m.group("rules")
                rest = m.group("rest") or ""
                sep = re.search(r"[^\w,\- ]", blob)
                ids_part = blob[: sep.start()] if sep else blob
                justification = (blob[sep.start():] if sep else "") + rest
                ids = {tok for part in ids_part.split(",")
                       for tok in part.strip().split() if tok}
                life_ids = ids & self._POLICED
                if life_ids and not re.search(r"\w", justification):
                    node = _LineNode(lineno)
                    project.report(
                        self, ctx, node,
                        f"suppression of {', '.join(sorted(life_ids))} has no "
                        "justification — append one after the rule id "
                        "(e.g. `# qrlife: disable=life-leak-on-raise — "
                        "proc stored by caller on the next line`)",
                    )


class _LineNode:
    """Minimal AST-node stand-in so line-anchored findings route through
    the normal report/suppression machinery."""

    def __init__(self, lineno: int):
        self.lineno = lineno
        self.end_lineno = lineno
        self.col_offset = 0


LIFE_RULES = (
    LockCycleRule, AwaitUnderLockRule, UnreleasedLockRule,
    LeakOnRaiseRule, DoubleReleaseRule, WipeGapRule,
    UnjustifiedLifeSuppressionRule,
)
