"""Lock-discipline analysis: registry, order graph, hold-site rules.

Three questions, answered project-wide over qrflow's call graph:

1. **Ordering** — every time a lock is acquired while another is held,
   that is an edge in the project lock-order graph.  Interprocedural:
   a call made under a held lock contributes edges to every lock the
   callee may transitively acquire (``call``/``await`` edges only —
   ``thread``/``task``/``executor`` edges run in a context that does
   NOT inherit the caller's held set).  A cycle in the graph is a
   potential deadlock (``life-lock-cycle``).
2. **Hold hygiene** — an ``await`` (or a known blocking call in a
   loop-domain function) while a *threading* lock is held stalls every
   other thread contending for it for an unbounded suspension
   (``life-await-under-lock``).  asyncio locks are await-shaped by
   design and are exempt.
3. **Release pairing** — a bare ``.acquire()`` whose ``.release()`` is
   not guaranteed on exception paths (``finally`` is the proof; the
   ``with`` statement is the better fix) is ``life-unreleased-lock``.
   ``__enter__``/``__exit__`` pairs and acquire/release wrapper methods
   are exempt — they ARE the context-manager implementation.

Lock identity is ``(owner, attribute)`` resolved through the call
graph's type machinery: ``self._lock`` keys as ``Owner._lock``,
``shard._lock`` resolves ``shard``'s inferred class, module-level locks
key as ``module.py::NAME``, function-local locks as the defining
function's qualname.  Unresolvable receivers are *skipped*, never
guessed — ``_lock`` is owned by many classes and a wrong guess would
invent cycles that do not exist.
"""

from __future__ import annotations

import ast
import dataclasses

from ..engine import FileContext, dotted_name, last_attr
from .callgraph_shim import CallGraph, FunctionInfo, ModuleInfo, walk_functions

#: constructor leaf -> lock kind (threading flavours block the OS thread;
#: asyncio flavours suspend the task and are await-safe)
_THREADING_CTORS = {"Lock": "lock", "RLock": "rlock", "Condition": "condition",
                    "Semaphore": "semaphore", "BoundedSemaphore": "semaphore"}
_ASYNC_CTORS = {"Lock": "async-lock", "Condition": "async-condition",
                "Semaphore": "async-semaphore",
                "BoundedSemaphore": "async-semaphore"}

#: kinds whose holders block an OS thread (await/blocking-call hazard)
THREADING_KINDS = frozenset({"lock", "rlock", "condition", "semaphore"})

#: kinds that participate in the order graph (semaphores are counters —
#: ordering between them is a throughput question, not a deadlock one)
ORDERED_KINDS = frozenset({"lock", "rlock", "condition",
                           "async-lock", "async-condition"})

#: calls that block the calling thread: flagged under a threading lock in
#: async/loop-domain code alongside ``await`` itself
_BLOCKING_DOTTED = {"time.sleep"}
_BLOCKING_LEAVES = {"recv", "recv_into", "accept", "connect", "sendall"}

#: method names that exempt a function from release-pairing checks — the
#: function IS the lock wrapper / context-manager implementation
_WRAPPER_NAMES = ("__enter__", "__exit__", "__aenter__", "__aexit__")


@dataclasses.dataclass
class LockDef:
    key: str            # stable identity: Owner.attr | mod.py::NAME | qualname::name
    kind: str           # lock | rlock | condition | semaphore | async-*
    ctx: FileContext
    node: ast.AST


@dataclasses.dataclass
class LockRef:
    """One resolved use of a lock at an acquisition site."""
    key: str
    kind: str
    via_self: bool      # acquired through ``self.<attr>``
    owner_class: str | None = None


@dataclasses.dataclass
class OrderEdge:
    src: str
    dst: str
    node: ast.AST       # the inner acquisition (or the call that reaches it)
    fn: FunctionInfo
    via: str = ""       # callee qualname for interprocedural edges
    src_self: bool = False
    dst_self: bool = False


@dataclasses.dataclass
class Hazard:
    rule: str
    fn: FunctionInfo
    node: ast.AST
    message: str


def _ctor_kind(call: ast.Call, mod: ModuleInfo) -> str | None:
    dotted = dotted_name(call.func) or ""
    leaf = last_attr(call.func) or ""
    if dotted.startswith("asyncio."):
        return _ASYNC_CTORS.get(leaf)
    if dotted.startswith(("threading.", "multiprocessing.")):
        return _THREADING_CTORS.get(leaf)
    if leaf in _THREADING_CTORS and leaf == dotted:  # bare name: check imports
        suffix, _orig = mod.imports.get(leaf, ("", None))
        if suffix == "asyncio":
            return _ASYNC_CTORS.get(leaf)
        return _THREADING_CTORS.get(leaf)
    return None


def _field_factory_kind(call: ast.Call, mod: ModuleInfo) -> str | None:
    """``field(default_factory=threading.Lock)`` in a dataclass body."""
    if (last_attr(call.func) or "") != "field":
        return None
    for kw in call.keywords:
        if kw.arg == "default_factory":
            fake = ast.Call(func=kw.value, args=[], keywords=[])
            return _ctor_kind(fake, mod)
    return None


class LockRegistry:
    """Every lock the project constructs, keyed by stable identity."""

    def __init__(self) -> None:
        self.defs: dict[str, LockDef] = {}
        self.class_attrs: dict[tuple[str, str], str] = {}   # (cls, attr) -> key
        self.module_level: dict[tuple[str, str], str] = {}  # (path, name) -> key
        self.fn_locals: dict[tuple[str, str], str] = {}     # (fid, name) -> key

    def _add(self, key: str, kind: str, ctx: FileContext, node: ast.AST) -> None:
        self.defs.setdefault(key, LockDef(key, kind, ctx, node))

    def build(self, cg: CallGraph) -> None:
        for mod in cg.modules.values():
            short = mod.path.rsplit("/", 1)[-1]
            for stmt in mod.ctx.tree.body:
                targets, value = _assign_parts(stmt)
                if value is None or not isinstance(value, ast.Call):
                    continue
                kind = _ctor_kind(value, mod)
                if kind is None:
                    continue
                for t in targets:
                    if isinstance(t, ast.Name):
                        key = f"{short}::{t.id}"
                        self._add(key, kind, mod.ctx, stmt)
                        self.module_level[(mod.path, t.id)] = key
            for cls in mod.classes.values():
                for stmt in cls.node.body:
                    targets, value = _assign_parts(stmt)
                    if value is None or not isinstance(value, ast.Call):
                        continue
                    kind = _ctor_kind(value, mod) or _field_factory_kind(value, mod)
                    if kind is None:
                        continue
                    for t in targets:
                        if isinstance(t, ast.Name):
                            key = f"{cls.name}.{t.id}"
                            self._add(key, kind, mod.ctx, stmt)
                            self.class_attrs[(cls.name, t.id)] = key
            for fn in walk_functions(mod):
                for stmt in _own_statements(fn):
                    targets, value = _assign_parts(stmt)
                    if value is None or not isinstance(value, ast.Call):
                        continue
                    kind = _ctor_kind(value, mod)
                    if kind is None:
                        continue
                    for t in targets:
                        if (isinstance(t, ast.Attribute)
                                and isinstance(t.value, ast.Name)
                                and t.value.id == "self" and fn.class_name):
                            key = f"{fn.class_name}.{t.attr}"
                            self._add(key, kind, mod.ctx, stmt)
                            self.class_attrs[(fn.class_name, t.attr)] = key
                        elif isinstance(t, ast.Name):
                            key = f"{fn.qualname}::{t.id}"
                            self._add(key, kind, mod.ctx, stmt)
                            self.fn_locals[(fn.fid, t.id)] = key

    # -- use-site resolution ------------------------------------------------

    def _class_attr_key(self, cg: CallGraph, cls: str, attr: str) -> str | None:
        seen: set[str] = set()
        queue = [cls]
        while queue:
            name = queue.pop()
            if name in seen:
                continue
            seen.add(name)
            key = self.class_attrs.get((name, attr))
            if key is not None:
                return key
            info = cg.classes.get(name)
            if info is not None:
                queue.extend(info.bases)
        return None

    def resolve(self, expr: ast.AST, fn: FunctionInfo, cg: CallGraph,
                local_types: dict[str, set[str]]) -> list[LockRef]:
        """Resolve a lock expression to registry identities (maybe several
        when the receiver's inferred type set is ambiguous; empty when the
        receiver cannot be typed — never guessed)."""
        if isinstance(expr, ast.Name):
            scope: FunctionInfo | None = fn
            while scope is not None:
                key = self.fn_locals.get((scope.fid, expr.id))
                if key is not None:
                    return [_ref(self.defs[key])]
                scope = scope.parent
            key = self.module_level.get((fn.path, expr.id))
            if key is not None:
                return [_ref(self.defs[key])]
            return []
        if not isinstance(expr, ast.Attribute):
            return []
        recv = expr.value
        if isinstance(recv, ast.Name) and recv.id == "self" and fn.class_name:
            key = self._class_attr_key(cg, fn.class_name, expr.attr)
            if key is not None:
                return [_ref(self.defs[key], via_self=True,
                             owner_class=fn.class_name)]
            return []
        if isinstance(recv, ast.Name):
            types = set(cg._lookup_types(recv.id, fn, local_types))
            if not types:
                types = _annotated_types(recv.id, fn, cg)
            out = []
            for cls in sorted(types):
                key = self._class_attr_key(cg, cls, expr.attr)
                if key is not None:
                    out.append(_ref(self.defs[key]))
            return out
        if (isinstance(recv, ast.Attribute) and isinstance(recv.value, ast.Name)
                and recv.value.id == "self" and fn.class_name):
            types = cg.class_attr_types.get(fn.class_name, {}).get(recv.attr, set())
            out = []
            for cls in sorted(types):
                key = self._class_attr_key(cg, cls, expr.attr)
                if key is not None:
                    out.append(_ref(self.defs[key]))
            return out
        return []


def _ref(d: LockDef, via_self: bool = False,
         owner_class: str | None = None) -> LockRef:
    return LockRef(d.key, d.kind, via_self, owner_class)


def _annotated_types(name: str, fn: FunctionInfo, cg: CallGraph) -> set[str]:
    """Parameter / AnnAssign annotations naming a known class — the one
    typing source the flow-insensitive local inference does not read."""
    def ann_leaf(ann: ast.AST) -> str | None:
        if isinstance(ann, ast.Constant) and isinstance(ann.value, str):
            # string annotation: take the trailing identifier
            tail = ann.value.strip().strip('"\'').split("|")[0].strip()
            return tail.split("[")[0].split(".")[-1] or None
        if isinstance(ann, ast.Subscript):   # Optional[X] / list[X]: unwrap
            return ann_leaf(ann.slice)
        if isinstance(ann, ast.BinOp) and isinstance(ann.op, ast.BitOr):
            return ann_leaf(ann.left)        # X | None
        return last_attr(ann)

    args = getattr(fn.node, "args", None)
    out: set[str] = set()
    if args is not None:
        for arg in [*args.posonlyargs, *args.args, *args.kwonlyargs]:
            if arg.arg == name and arg.annotation is not None:
                leaf = ann_leaf(arg.annotation)
                if leaf and leaf in cg.classes:
                    out.add(leaf)
    for stmt in _own_statements(fn):
        if (isinstance(stmt, ast.AnnAssign) and isinstance(stmt.target, ast.Name)
                and stmt.target.id == name and stmt.annotation is not None):
            leaf = ann_leaf(stmt.annotation)
            if leaf and leaf in cg.classes:
                out.add(leaf)
    return out


def _assign_parts(stmt: ast.stmt) -> tuple[list[ast.AST], ast.AST | None]:
    if isinstance(stmt, ast.Assign):
        return list(stmt.targets), stmt.value
    if isinstance(stmt, ast.AnnAssign) and stmt.value is not None:
        return [stmt.target], stmt.value
    return [], None


def _own_statements(fn: FunctionInfo):
    def walk(stmts):
        for stmt in stmts:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            yield stmt
            for field in ("body", "orelse", "finalbody"):
                yield from walk(getattr(stmt, field, []) or [])
            for handler in getattr(stmt, "handlers", []) or []:
                yield from walk(handler.body)
    yield from walk(getattr(fn.node, "body", []))


def _stmt_can_raise(stmt: ast.stmt) -> bool:
    for node in ast.walk(stmt):
        if isinstance(node, (ast.Call, ast.Await, ast.Raise, ast.Assert)):
            return True
    return False


class LockAnalysis:
    """Per-function walks + interprocedural order graph + hazards."""

    def __init__(self, cg: CallGraph, domains: dict[str, set[str]]):
        self.cg = cg
        self.domains = domains
        self.registry = LockRegistry()
        self.registry.build(cg)
        self.edges: list[OrderEdge] = []
        self.hazards: list[Hazard] = []
        #: fid -> keys the function acquires directly (for interprocedural
        #: may-acquire propagation), with via-self class tags
        self.direct: dict[str, set[tuple[str, str | None]]] = {}
        #: (call node id) -> held refs at that call
        self._calls_under: list[tuple[ast.Call | ast.Await, tuple[LockRef, ...],
                                      FunctionInfo]] = []
        for mod in cg.modules.values():
            for fn in walk_functions(mod):
                self._walk_fn(fn, mod)
        self._interprocedural()

    # -- per-function -------------------------------------------------------

    def _walk_fn(self, fn: FunctionInfo, mod: ModuleInfo) -> None:
        local_types = self.cg._local_types_of(fn, mod)
        acquired = self.direct.setdefault(fn.fid, set())
        on_loop = fn.is_async or "loop" in self.domains.get(fn.fid, set())

        def resolve(expr: ast.AST) -> list[LockRef]:
            return self.registry.resolve(expr, fn, self.cg, local_types)

        def note_acquire(refs: list[LockRef], node: ast.AST,
                         held: tuple[LockRef, ...]) -> None:
            for ref in refs:
                acquired.add((ref.key, ref.owner_class if ref.via_self else None))
                for h in held:
                    if h.key == ref.key and not (h.via_self and ref.via_self
                                                 and h.kind == "lock"):
                        continue  # self-edge only for non-reentrant self locks
                    if (h.kind in ORDERED_KINDS and ref.kind in ORDERED_KINDS):
                        self.edges.append(OrderEdge(
                            h.key, ref.key, node, fn,
                            src_self=h.via_self, dst_self=ref.via_self))

        def visit_block(stmts: list[ast.stmt], held: tuple[LockRef, ...]) -> None:
            for stmt in stmts:
                visit_stmt(stmt, held)

        def visit_stmt(stmt: ast.stmt, held: tuple[LockRef, ...]) -> None:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                return  # nested defs run later, not under this held set
            if isinstance(stmt, (ast.With, ast.AsyncWith)):
                new = list(held)
                for item in stmt.items:
                    ce = item.context_expr
                    expr = ce
                    if isinstance(ce, ast.Call) and (last_attr(ce.func) or "") in (
                            "acquire", "acquire_lock"):
                        expr = ce.func.value if isinstance(ce.func, ast.Attribute) else ce
                    refs = resolve(expr)
                    if refs:
                        note_acquire(refs, stmt, tuple(new))
                        new.extend(refs)
                    else:
                        self._scan_expr(ce, fn, tuple(new), on_loop)
                visit_block(stmt.body, tuple(new))
                return
            for expr in _stmt_exprs(stmt):
                self._scan_expr(expr, fn, held, on_loop)
            if isinstance(stmt, ast.Expr) and _is_acquire_call(stmt.value):
                call = _strip_await(stmt.value)
                recv = call.func.value  # type: ignore[union-attr]
                refs = resolve(recv)
                if refs:
                    note_acquire(refs, stmt, held)
                    held = tuple([*held, *refs])
            for field in ("body", "orelse", "finalbody"):
                visit_block(getattr(stmt, field, []) or [], held)
            for handler in getattr(stmt, "handlers", []) or []:
                visit_block(handler.body, held)

        visit_block(getattr(fn.node, "body", []), ())
        self._check_release_pairing(fn, resolve)

    def _scan_expr(self, expr: ast.AST, fn: FunctionInfo,
                   held: tuple[LockRef, ...], on_loop: bool) -> None:
        """Record await/blocking hazards and calls made under held locks."""
        threading_held = [h for h in held if h.kind in THREADING_KINDS]
        for node in ast.walk(expr):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda)):
                continue
            if isinstance(node, ast.Await):
                if threading_held:
                    self.hazards.append(Hazard(
                        "life-await-under-lock", fn, node,
                        f"await while holding threading lock "
                        f"{threading_held[0].key}: every thread contending "
                        "for it blocks for the whole suspension — release "
                        "before awaiting, or use asyncio.Lock"))
                if held:
                    self._calls_under.append((node, held, fn))
            elif isinstance(node, ast.Call):
                dotted = dotted_name(node.func) or ""
                leaf = last_attr(node.func) or ""
                if threading_held and on_loop and (
                        dotted in _BLOCKING_DOTTED
                        or (leaf in _BLOCKING_LEAVES
                            and isinstance(node.func, ast.Attribute))):
                    self.hazards.append(Hazard(
                        "life-await-under-lock", fn, node,
                        f"blocking call {dotted or leaf}() while holding "
                        f"threading lock {threading_held[0].key} in "
                        "event-loop code — the loop and every lock waiter "
                        "stall together"))
                if held:
                    self._calls_under.append((node, held, fn))

    # -- release pairing ----------------------------------------------------

    def _check_release_pairing(self, fn: FunctionInfo, resolve) -> None:
        name = fn.name
        if name in _WRAPPER_NAMES or any(
                w in name for w in ("acquire", "release", "lock", "unlock")):
            return

        def releases_in(stmts: list[ast.stmt], key: str) -> bool:
            for stmt in stmts:
                for node in ast.walk(stmt):
                    if (isinstance(node, ast.Call)
                            and isinstance(node.func, ast.Attribute)
                            and node.func.attr in ("release", "release_lock")):
                        for ref in resolve(node.func.value):
                            if ref.key == key:
                                return True
            return False

        def enclosing_finally_releases(stack: list[ast.stmt], key: str) -> bool:
            return any(isinstance(s, ast.Try) and releases_in(s.finalbody, key)
                       for s in stack)

        def visit(stmts: list[ast.stmt], stack: list[ast.stmt]) -> None:
            for i, stmt in enumerate(stmts):
                if isinstance(stmt, ast.Expr) and _is_acquire_call(stmt.value):
                    call = _strip_await(stmt.value)
                    recv = call.func.value  # type: ignore[union-attr]
                    for ref in resolve(recv):
                        if ref.kind not in THREADING_KINDS | {
                                "async-lock", "async-semaphore",
                                "async-condition"}:
                            continue
                        if enclosing_finally_releases(stack, ref.key):
                            continue
                        nxt = stmts[i + 1] if i + 1 < len(stmts) else None
                        if isinstance(nxt, ast.Try) and releases_in(
                                nxt.finalbody, ref.key):
                            continue
                        rest = stmts[i + 1:]
                        released_later = releases_in(rest, ref.key)
                        risky = any(_stmt_can_raise(s) for s in rest
                                    if not releases_in([s], ref.key))
                        if released_later and not risky:
                            continue
                        if released_later:
                            msg = (f"{ref.key}.acquire() is released later in "
                                   "this block, but an exception in between "
                                   "skips the release — move release() into "
                                   "a finally, or use `with`")
                        else:
                            msg = (f"{ref.key}.acquire() has no matching "
                                   "release() on this function's exception "
                                   "paths — use `with` or try/finally")
                        self.hazards.append(Hazard(
                            "life-unreleased-lock", fn, stmt, msg))
                for field in ("body", "orelse", "finalbody"):
                    sub = getattr(stmt, field, []) or []
                    if sub:
                        visit(sub, stack + [stmt])
                for handler in getattr(stmt, "handlers", []) or []:
                    visit(handler.body, stack + [stmt])

        visit(getattr(fn.node, "body", []), [])

    # -- interprocedural ----------------------------------------------------

    def _interprocedural(self) -> None:
        may: dict[str, set[tuple[str, str | None]]] = {
            fid: set(keys) for fid, keys in self.direct.items()}
        changed = True
        while changed:
            changed = False
            for site in self.cg.edges:
                if site.kind not in ("call", "await"):
                    continue
                src = may.setdefault(site.caller.fid, set())
                add = {(k, None) for (k, _cls) in may.get(site.callee.fid, ())}
                if not add <= src:
                    src |= add
                    changed = True
        for node, held, fn in self._calls_under:
            for site in self.cg.edges_at.get(id(node), []):
                if site.kind not in ("call", "await"):
                    continue
                callee = site.callee
                for (key, _cls) in may.get(callee.fid, ()):
                    kind = self.registry.defs[key].kind
                    if kind not in ORDERED_KINDS:
                        continue
                    for h in held:
                        if h.kind not in ORDERED_KINDS:
                            continue
                        if h.key == key:
                            # interprocedural self-deadlock: only claimed for
                            # a non-reentrant lock reached via a direct
                            # self-call within the same class
                            direct = (key, fn.class_name) in self.direct.get(
                                callee.fid, set())
                            if not (kind == "lock" and h.via_self and direct
                                    and callee.class_name == fn.class_name):
                                continue
                        self.edges.append(OrderEdge(
                            h.key, key, node, fn, via=callee.qualname,
                            src_self=h.via_self))

    # -- cycles -------------------------------------------------------------

    def cycles(self) -> list[list[OrderEdge]]:
        adj: dict[str, dict[str, OrderEdge]] = {}
        for e in self.edges:
            adj.setdefault(e.src, {}).setdefault(e.dst, e)
        out: list[list[OrderEdge]] = []
        seen_cycles: set[tuple[str, ...]] = set()

        def dfs(start: str, node: str, path: list[OrderEdge],
                on_path: set[str]) -> None:
            for dst in sorted(adj.get(node, ())):
                edge = adj[node][dst]
                if dst == start:
                    cyc = path + [edge]
                    keys = [e.src for e in cyc]
                    canon = tuple(sorted(keys))
                    if canon not in seen_cycles:
                        seen_cycles.add(canon)
                        out.append(cyc)
                elif dst not in on_path and dst > start:
                    # only walk "later" nodes so each cycle is found once,
                    # rooted at its smallest key
                    dfs(start, dst, path + [edge], on_path | {dst})

        for start in sorted(adj):
            dfs(start, start, [], {start})
        return out


def _stmt_exprs(stmt: ast.stmt):
    """Expressions evaluated by this statement itself (not child blocks)."""
    for field, value in ast.iter_fields(stmt):
        if field in ("body", "orelse", "finalbody", "handlers"):
            continue
        if isinstance(value, ast.expr):
            yield value
        elif isinstance(value, list):
            for v in value:
                if isinstance(v, ast.expr):
                    yield v


def _strip_await(expr: ast.AST) -> ast.AST:
    return expr.value if isinstance(expr, ast.Await) else expr


def _is_acquire_call(expr: ast.AST) -> bool:
    call = _strip_await(expr)
    return (isinstance(call, ast.Call)
            and isinstance(call.func, ast.Attribute)
            and call.func.attr in ("acquire", "acquire_lock"))
