"""qrlife's import surface over qrflow's call graph.

qrlife deliberately reuses qrflow's interprocedural machinery instead of
growing a second call-graph implementation; this shim pins exactly which
pieces the lifetime analyses depend on (and re-exports the two private
walkers so the dependency is explicit rather than scattered
``from ..flow.callgraph import _x`` lines).
"""

from __future__ import annotations

from ..flow.callgraph import (CallGraph, CallSite, ClassInfo, FunctionInfo,
                              ModuleInfo, build_callgraph)
from ..flow.callgraph import _own_statements as own_statements
from ..flow.callgraph import _walk_functions as walk_functions

__all__ = [
    "CallGraph", "CallSite", "ClassInfo", "FunctionInfo", "ModuleInfo",
    "build_callgraph", "own_statements", "walk_functions",
]
