"""Resource-lifetime analysis: acquire/release pairing on exception paths.

Tracks the resources the fleet actually leaks — subprocess spawns,
sockets/StreamWriters, thread pools, telemetry servers, tempdirs,
asyncio tasks — from the statement that binds them to a local through
the rest of the enclosing function.  A resource obligation is
*discharged* by one of the blessed proofs:

* acquired under ``with``/``async with`` (never tracked at all);
* a release verb for its kind (``close``/``terminate``/``shutdown``/
  ``cleanup``/``cancel``/…), anywhere downstream — a conditional
  release counts: one branch releasing is evidence of deliberate
  conditional ownership, and guessing the condition would only invent
  false positives;
* a ``finally`` that releases it (everything inside the ``try`` is then
  proven, which is exactly why the idiom is blessed);
* ``add_done_callback``/``await task``/``gather(...)`` for tasks;
* **escape** — returned, yielded, stored into an attribute, container,
  or registry, or handed to a method of another object.  Ownership
  moved; the new owner's lifecycle is its own analysis problem
  (qrlint's zeroize/teardown rules police attributes).

Between acquisition and discharge, any statement that can raise (a
call, an ``await`` — CancelledError needs no reason — an explicit
``raise``) makes the leak reachable: ``life-leak-on-raise`` fires at
the acquisition with the first unprotected raise site named.

``life-double-release`` is the narrow dual: the same release verb on
the same receiver twice, unconditionally, in one straight-line block —
dead code at best (idempotent ``close``) and a crash at worst
(``lock.release()``, ``os.close``).
"""

from __future__ import annotations

import ast
import dataclasses

from ..engine import dotted_name, last_attr
from .callgraph_shim import CallGraph, FunctionInfo, ModuleInfo, walk_functions


@dataclasses.dataclass(frozen=True)
class ResourceSpec:
    kind: str
    releases: frozenset[str]
    tuple_index: int | None = None   # which unpack element carries the resource


def _spec(kind: str, *releases: str, tuple_index: int | None = None) -> ResourceSpec:
    return ResourceSpec(kind, frozenset(releases), tuple_index)


#: acquisition call leaf -> what was acquired and how it is released
RESOURCES: dict[str, ResourceSpec] = {
    "open_connection": _spec("stream-writer", "close", "abort", tuple_index=1),
    "start_server": _spec("server", "close"),
    "start_unix_server": _spec("server", "close"),
    "create_subprocess_exec": _spec("subprocess", "terminate", "kill", "wait",
                                    "communicate"),
    "create_subprocess_shell": _spec("subprocess", "terminate", "kill", "wait",
                                     "communicate"),
    "Popen": _spec("subprocess", "terminate", "kill", "wait", "communicate"),
    "ThreadPoolExecutor": _spec("executor", "shutdown"),
    "ProcessPoolExecutor": _spec("executor", "shutdown"),
    "TelemetryServer": _spec("telemetry-server", "stop", "close", "shutdown"),
    "mkdtemp": _spec("tempdir", "cleanup", "rmtree"),
    "TemporaryDirectory": _spec("tempdir", "cleanup"),
    "NamedTemporaryFile": _spec("tempfile", "close"),
    "create_task": _spec("task", "cancel"),
    "ensure_future": _spec("task", "cancel"),
    "socket": _spec("socket", "close", "detach", "shutdown"),
    "create_connection": _spec("socket", "close", "detach", "shutdown"),
}

#: leaves that must carry a dotted prefix to count (``socket.socket``) —
#: a bare name with these leaves is too ambiguous to claim
_NEED_PREFIX = {"socket": ("socket.socket",),
                "create_connection": ("socket.create_connection",)}

#: calls that take ownership of a task passed as an argument
_TASK_SINKS = {"gather", "wait", "wait_for", "as_completed", "shield"}

#: release verbs for the straight-line double-release check
_DOUBLE_VERBS = {"close", "cancel", "shutdown", "terminate", "kill",
                 "cleanup", "stop", "release", "abort"}

#: calls that never raise in practice — don't make a leak reachable
_SAFE_DOTTED = {"time.monotonic", "time.time", "time.perf_counter",
                "asyncio.Lock", "asyncio.Event", "asyncio.Queue",
                "asyncio.Semaphore", "threading.Lock", "threading.Event",
                "threading.RLock"}
_SAFE_LEAVES = {"create_task", "ensure_future", "set", "list", "dict",
                "tuple", "frozenset", "len", "min", "max", "sorted", "sum",
                "int", "float", "str", "bool"}
_SAFE_LOG_METHODS = {"debug", "info", "warning", "error", "exception",
                     "critical", "log"}

_DISCHARGED, _LEAK, _FALLTHROUGH = "discharged", "leak", "fallthrough"


@dataclasses.dataclass
class Leak:
    rule: str
    fn: FunctionInfo
    node: ast.AST
    message: str


def _unwrap_value(expr: ast.AST) -> ast.AST:
    """Peel ``await`` and ``wait_for``/``shield`` wrappers off an
    acquisition expression."""
    if isinstance(expr, ast.Await):
        expr = expr.value
    if (isinstance(expr, ast.Call)
            and (last_attr(expr.func) or "") in ("wait_for", "shield")
            and expr.args):
        inner = expr.args[0]
        if isinstance(inner, ast.Call):
            return inner
    return expr


def _assign_target_value(stmt: ast.stmt) -> tuple[ast.AST, ast.AST] | None:
    if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1:
        return stmt.targets[0], stmt.value
    if isinstance(stmt, ast.AnnAssign) and stmt.value is not None:
        return stmt.target, stmt.value
    return None


def _acquisition(stmt: ast.stmt) -> tuple[str, ResourceSpec, ast.stmt] | None:
    """``name = <resource ctor>`` (or tuple-unpack thereof) -> obligation."""
    parts = _assign_target_value(stmt)
    if parts is None:
        return None
    target, raw = parts
    value = _unwrap_value(raw)
    if not isinstance(value, ast.Call):
        return None
    leaf = last_attr(value.func) or ""
    spec = RESOURCES.get(leaf)
    if spec is None:
        return None
    dotted = dotted_name(value.func) or leaf
    need = _NEED_PREFIX.get(leaf)
    if need and dotted not in need:
        return None
    if spec.tuple_index is not None and isinstance(target, ast.Tuple):
        if len(target.elts) > spec.tuple_index:
            el = target.elts[spec.tuple_index]
            if isinstance(el, ast.Name):
                return el.id, spec, stmt
        return None
    if isinstance(target, ast.Name):
        return target.id, spec, stmt
    return None  # attribute/subscript target: escaped at birth


def _is_module_alias(name: str, mod: ModuleInfo) -> bool:
    entry = mod.imports.get(name)
    return entry is not None


class _Tracker:
    """Follows one resource local through the rest of its function."""

    def __init__(self, name: str, spec: ResourceSpec, mod: ModuleInfo):
        self.name = name
        self.spec = spec
        self.mod = mod

    # -- event classification ----------------------------------------------

    def _releases(self, node: ast.AST) -> bool:
        if (isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute)
                and isinstance(node.func.value, ast.Name)
                and node.func.value.id == self.name
                and node.func.attr in self.spec.releases):
            return True
        if self.spec.kind == "tempdir" and isinstance(node, ast.Call):
            leaf = last_attr(node.func) or ""
            if leaf == "rmtree" and any(
                    isinstance(a, ast.Name) and a.id == self.name
                    for a in node.args):
                return True
        return False

    def _escapes(self, node: ast.AST) -> bool:
        name = self.name
        if isinstance(node, (ast.Return, ast.Yield, ast.YieldFrom)):
            return any(isinstance(n, ast.Name) and n.id == name
                       for n in ast.walk(node))
        if isinstance(node, (ast.Assign, ast.AnnAssign)):
            parts = _assign_target_value(node)
            if parts is None:
                return False
            target, value = parts
            holds = any(isinstance(n, ast.Name) and n.id == name
                        and isinstance(n.ctx, ast.Load)
                        for n in ast.walk(value))
            return holds and not isinstance(target, ast.Name)
        if isinstance(node, ast.Call):
            in_args = any(
                isinstance(n, ast.Name) and n.id == name
                for a in [*node.args, *[kw.value for kw in node.keywords]]
                for n in ast.walk(a))
            if not in_args:
                return False
            leaf = last_attr(node.func) or ""
            if self.spec.kind == "task" and leaf in _TASK_SINKS:
                return True
            # handed to a METHOD of some object (registry.add(w),
            # self._track(proc), stack.enter_context(...)): ownership
            # transfer.  A plain function using the resource is not.
            if isinstance(node.func, ast.Attribute):
                recv = node.func.value
                if isinstance(recv, ast.Name) and _is_module_alias(
                        recv.id, self.mod):
                    return False
                if isinstance(recv, ast.Name) and recv.id == name:
                    return False  # method on the resource itself is usage
                return True
        return False

    def _task_discharge(self, node: ast.AST) -> bool:
        if self.spec.kind != "task":
            return False
        if (isinstance(node, ast.Await) and isinstance(node.value, ast.Name)
                and node.value.id == self.name):
            return True
        return (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and isinstance(node.func.value, ast.Name)
                and node.func.value.id == self.name
                and node.func.attr == "add_done_callback")

    def discharged_in(self, root: ast.AST) -> bool:
        for node in ast.walk(root):
            if (self._releases(node) or self._escapes(node)
                    or self._task_discharge(node)):
                return True
            if isinstance(node, ast.Delete) and any(
                    isinstance(t, ast.Name) and t.id == self.name
                    for t in node.targets):
                return True
        return False

    def reassigned_in(self, stmt: ast.stmt) -> bool:
        if isinstance(stmt, (ast.Assign, ast.AnnAssign)):
            parts = _assign_target_value(stmt)
            if parts is not None:
                target, _value = parts
                if isinstance(target, ast.Name) and target.id == self.name:
                    return True
        if isinstance(stmt, (ast.For, ast.AsyncFor)):
            return (isinstance(stmt.target, ast.Name)
                    and stmt.target.id == self.name)
        return False

    def can_raise(self, stmt: ast.stmt) -> ast.AST | None:
        """First raise-capable node in a statement, with a small allowlist
        of never-raising calls (logging, clock reads, task spawns — their
        argument subtrees only build coroutine objects, they don't run)."""

        def safe_call(node: ast.Call) -> bool:
            dotted = dotted_name(node.func) or ""
            if dotted in _SAFE_DOTTED:
                return True
            if (last_attr(node.func) or "") in _SAFE_LEAVES:
                return True
            return (isinstance(node.func, ast.Attribute)
                    and node.func.attr in _SAFE_LOG_METHODS
                    and isinstance(node.func.value, ast.Name)
                    and "log" in node.func.value.id.lower())

        def first(node: ast.AST) -> ast.AST | None:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda)):
                return None   # a def only binds a name; its body runs later
            if isinstance(node, (ast.Raise, ast.Await)):
                return node
            if isinstance(node, ast.Call):
                if safe_call(node):
                    return None   # safe wrapper: its args never execute/raise
                return node
            for child in ast.iter_child_nodes(node):
                got = first(child)
                if got is not None:
                    return got
            return None

        return first(stmt)


def _child_blocks(stmt: ast.stmt):
    for field in ("body", "orelse", "finalbody"):
        sub = getattr(stmt, field, None)
        if sub:
            yield sub
    for handler in getattr(stmt, "handlers", []) or []:
        yield handler.body


def scan_function(fn: FunctionInfo, mod: ModuleInfo, out: list[Leak]) -> None:
    body = getattr(fn.node, "body", [])

    def follow(tr: _Tracker, frames: list[tuple[list[ast.stmt], int]],
               acq: ast.stmt) -> None:
        for stmts, start in frames:
            for stmt in stmts[start:]:
                status = _step(tr, stmt)
                if status == _DISCHARGED:
                    return
                if isinstance(status, tuple):       # (_LEAK, at-node)
                    _, at = status
                    line = getattr(at, "lineno", getattr(acq, "lineno", 0))
                    out.append(Leak(
                        "life-leak-on-raise", fn, acq,
                        f"{tr.spec.kind} bound to `{tr.name}` can leak: "
                        f"line {line} can raise before any release/escape "
                        "— wrap the risky region in try/finally, use a "
                        "context manager, or hand ownership off first"))
                    return
        # fell off the function with the obligation still live
        out.append(Leak(
            "life-leak-on-raise", fn, acq,
            f"{tr.spec.kind} bound to `{tr.name}` is never released, "
            "stored, or returned on any path through "
            f"{fn.qualname}() — close it or transfer ownership"))

    def _step(tr: _Tracker, stmt: ast.stmt):
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return _FALLTHROUGH   # nested def: not executed here
        if tr.reassigned_in(stmt):
            return _DISCHARGED    # rebound; the old value is out of scope
        if isinstance(stmt, ast.Try):
            if any(tr.discharged_in(s) for s in stmt.finalbody):
                return _DISCHARGED
            if tr.discharged_in(stmt):
                return _DISCHARGED
            at = tr.can_raise(stmt)
            return (_LEAK, at) if at is not None else _FALLTHROUGH
        if tr.discharged_in(stmt):
            return _DISCHARGED
        at = tr.can_raise(stmt)
        if at is not None:
            return (_LEAK, at)
        return _FALLTHROUGH

    def scan_block(stmts: list[ast.stmt],
                   conts: list[tuple[list[ast.stmt], int]],
                   finals: list[list[ast.stmt]]) -> None:
        for i, stmt in enumerate(stmts):
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            got = _acquisition(stmt)
            if got is not None:
                name, spec, node = got
                tr = _Tracker(name, spec, mod)
                # an enclosing finally that releases it is the blessed
                # proof no matter where inside the try we are
                if not any(tr.discharged_in(s)
                           for final in finals for s in final):
                    follow(tr, [(stmts, i + 1)] + conts, node)
            if isinstance(stmt, ast.Try):
                inner = finals + [stmt.finalbody] if stmt.finalbody else finals
                for field in ("body", "orelse"):
                    sub = getattr(stmt, field, None)
                    if sub:
                        scan_block(sub, [(stmts, i + 1)] + conts, inner)
                for handler in stmt.handlers:
                    scan_block(handler.body, [(stmts, i + 1)] + conts, inner)
                if stmt.finalbody:
                    scan_block(stmt.finalbody, [(stmts, i + 1)] + conts,
                               finals)
            else:
                for block in _child_blocks(stmt):
                    scan_block(block, [(stmts, i + 1)] + conts, finals)

    scan_block(body, [], [])
    _double_release(fn, out)


def _double_release(fn: FunctionInfo, out: list[Leak]) -> None:
    def recv_key(call: ast.Call) -> str | None:
        func = call.func
        if not isinstance(func, ast.Attribute):
            return None
        recv = dotted_name(func.value)
        return recv

    def scan(stmts: list[ast.stmt]) -> None:
        seen: dict[tuple[str, str], ast.stmt] = {}
        for stmt in stmts:
            if isinstance(stmt, ast.Assign):
                for t in stmt.targets:
                    tn = dotted_name(t)
                    if tn:
                        for key in [k for k in seen if k[0] == tn]:
                            del seen[key]
            if (isinstance(stmt, ast.Expr)):
                call = stmt.value
                if isinstance(call, ast.Await):
                    call = call.value
                if (isinstance(call, ast.Call)
                        and isinstance(call.func, ast.Attribute)
                        and call.func.attr in _DOUBLE_VERBS):
                    recv = recv_key(call)
                    if recv:
                        key = (recv, call.func.attr)
                        if key in seen:
                            out.append(Leak(
                                "life-double-release", fn, stmt,
                                f"{recv}.{call.func.attr}() already called "
                                f"unconditionally at line "
                                f"{getattr(seen[key], 'lineno', '?')} in this "
                                "block — the second call is dead code or a "
                                "double release"))
                        else:
                            seen[key] = stmt
            for block in _child_blocks(stmt):
                scan(block)

    scan(getattr(fn.node, "body", []))


def run_resources(cg: CallGraph) -> list[Leak]:
    out: list[Leak] = []
    for mod in cg.modules.values():
        for fn in walk_functions(mod):
            scan_function(fn, mod, out)
    return out
