"""Secret-lifetime completeness: every SECRET local reaches a wipe.

Generalizes PR 4's hand-maintained discipline — shared secrets, ticket
master secrets, and decapsulation outputs are wiped on the paths someone
remembered — into a checked property: any local bound from a SECRET
source in qrflow's taint lattice (``decapsulate``, ``open_ticket``'s
secret element, ``derive_resumption_secret``, …; the source set is
imported from the lattice's crypto-op MODELS, never duplicated) must
reach ``_wipe()``/``zeroize()`` on **every** explicit function exit
path, unless ownership escapes first (returned, stored into an object's
state — attribute zeroization is qrlint's beat — or handed to a
container).

Discharge events per secret local:

* any ``WIPERS`` call taking it (``_wipe(ss)``) or receiver-form
  ``ss.zeroize()``;
* a ``bytearray(ss)``/``bytes(ss)`` rebind — the wipeable twin inherits
  the obligation and the immutable original is unredeemable by
  construction (flagging it would demand the impossible);
* escape: ``return``/``yield``, attribute/subscript store, or a storing
  method call (``append``/``add``/``put``/``setdefault``/…).

Passing a secret to a KDF does NOT discharge it — the caller still
holds the buffer; that is precisely the rekey-path bug class this rule
exists for.  A wipe inside an enclosing ``finally`` covers every exit
inside that ``try``.

Scope: ``pyref/`` is excluded (pure-Python FIPS references — secret
arithmetic IS the algorithm there, mirroring qrflow's CT_EXCLUDE), and
functions that *are* wipers or sources are exempt (their internals are
the implementation being modelled).

Known limitation (documented contract): v1 proves explicit exits —
``return`` statements and fall-off-the-end.  Exception-edge
completeness composes with ``life-leak-on-raise``'s ``finally``
discipline rather than duplicating it.
"""

from __future__ import annotations

import ast
import dataclasses

from ..engine import last_attr
from ..flow.taint import MODELS, SECRET, WIPERS
from .callgraph_shim import CallGraph, FunctionInfo, walk_functions

#: paths excluded from wipe-completeness (see module doc)
WIPE_EXCLUDE = ("pyref/", "pyref\\")

#: call leaves whose whole return value is SECRET / whose tuple elements
#: are — derived from qrflow's MODELS so the two analyzers can never drift
SECRET_CALLS: dict[str, tuple[int, ...] | None] = {}
for _name, _taint in MODELS.items():
    if _taint.level != SECRET:
        continue
    if _taint.elements is None:
        SECRET_CALLS[_name] = None          # whole value is secret
    else:
        idxs = tuple(i for i, el in enumerate(_taint.elements)
                     if el.level == SECRET)
        if idxs:
            SECRET_CALLS[_name] = idxs       # these unpack elements are

#: receiver-method calls that store their argument somewhere longer-lived
_STORING_METHODS = {"append", "add", "put", "put_nowait", "insert",
                    "setdefault", "store", "extend"}

_LIVE, _WIPED, _ESCAPED = "live", "wiped", "escaped"


@dataclasses.dataclass
class WipeGap:
    fn: FunctionInfo
    node: ast.AST
    message: str


def _source_of(value: ast.AST) -> tuple[str, tuple[int, ...] | None] | None:
    if isinstance(value, ast.Await):
        value = value.value
    if not isinstance(value, ast.Call):
        return None
    leaf = last_attr(value.func) or ""
    if leaf in SECRET_CALLS:
        return leaf, SECRET_CALLS[leaf]
    return None


def _names_in(expr: ast.AST) -> set[str]:
    return {n.id for n in ast.walk(expr)
            if isinstance(n, ast.Name) and isinstance(n.ctx, ast.Load)}


class _FnWipeScan:
    def __init__(self, fn: FunctionInfo, out: list[WipeGap]):
        self.fn = fn
        self.out = out
        self.sources: dict[str, str] = {}      # local -> provenance
        self.reported: set[str] = set()
        self.finally_wiped: list[set[str]] = []  # stack of enclosing covers

    def run(self) -> None:
        state = self._exec_block(getattr(self.fn.node, "body", []), {})
        if state is not None:
            self._check_exit(state, self.fn.node, "falls off the end")

    # -- state machine ------------------------------------------------------

    def _exec_block(self, stmts: list[ast.stmt],
                    state: dict[str, str] | None) -> dict[str, str] | None:
        for stmt in stmts:
            if state is None:
                return None
            state = self._exec_stmt(stmt, state)
        return state

    def _exec_stmt(self, stmt: ast.stmt,
                   state: dict[str, str]) -> dict[str, str] | None:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            return state
        if isinstance(stmt, ast.Return):
            if stmt.value is not None:
                # the returned value is an ownership transfer, not a gap
                self._mark_escapes(_names_in(stmt.value), state)
            self._check_exit(state, stmt,
                             f"returns at line {stmt.lineno}")
            return None
        if isinstance(stmt, ast.Raise):
            return None   # exception exits compose with life-leak-on-raise
        if isinstance(stmt, ast.Assign):
            self._scan_events(stmt, state)
            self._bind(stmt, state)
            return state
        if isinstance(stmt, ast.If):
            self._scan_events_expr(stmt.test, state)
            a = self._exec_block(stmt.body, dict(state))
            b = self._exec_block(stmt.orelse, dict(state))
            return _merge(a, b)
        if isinstance(stmt, ast.Try):
            cover = set()
            for s in stmt.finalbody:
                cover |= self._wipes_in(s)
            self.finally_wiped.append(cover)
            try:
                body = self._exec_block(stmt.body, dict(state))
                if stmt.orelse and body is not None:
                    body = self._exec_block(stmt.orelse, dict(body))
                merged = body
                for handler in stmt.handlers:
                    merged = _merge(merged,
                                    self._exec_block(handler.body, dict(state)))
            finally:
                self.finally_wiped.pop()
            return self._exec_block(stmt.finalbody,
                                    merged if merged is not None else dict(state))
        if isinstance(stmt, (ast.For, ast.AsyncFor, ast.While)):
            for expr in _stmt_exprs(stmt):
                self._scan_events_expr(expr, state)
            once = self._exec_block(stmt.body, dict(state))
            merged = _merge(once, state)
            return self._exec_block(stmt.orelse, merged) if stmt.orelse else merged
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            for item in stmt.items:
                self._scan_events_expr(item.context_expr, state)
            return self._exec_block(stmt.body, state)
        for expr in _stmt_exprs(stmt):
            self._scan_events_expr(expr, state)
        return state

    # -- events -------------------------------------------------------------

    def _bind(self, stmt: ast.Assign, state: dict[str, str]) -> None:
        if len(stmt.targets) != 1:
            return
        target = stmt.targets[0]
        src = _source_of(stmt.value)
        if src is not None:
            leaf, idxs = src
            if idxs is None and isinstance(target, ast.Name):
                self.sources[target.id] = f"{leaf}()"
                state[target.id] = _LIVE
            elif idxs is not None and isinstance(target, ast.Tuple):
                for i in idxs:
                    if i < len(target.elts) and isinstance(
                            target.elts[i], ast.Name):
                        name = target.elts[i].id
                        if name == "_":   # explicit discard placeholder
                            continue
                        self.sources[name] = f"{leaf}()[{i}]"
                        state[name] = _LIVE
            return
        # bytearray/bytes twin: the wipeable copy inherits the obligation
        value = stmt.value
        if (isinstance(value, ast.Call)
                and (last_attr(value.func) or "") in ("bytearray", "bytes")
                and value.args and isinstance(value.args[0], ast.Name)
                and isinstance(target, ast.Name)):
            old = value.args[0].id
            if state.get(old) == _LIVE:
                state[old] = _ESCAPED
                self.sources[target.id] = self.sources.get(
                    old, "secret") + " via bytearray copy"
                state[target.id] = _LIVE
                return
        # plain rebind of a tracked name drops the old obligation silently
        # only when the old value was already handled; a live rebind is a
        # lost buffer
        if isinstance(target, ast.Name) and state.get(target.id) == _LIVE:
            self.out.append(WipeGap(
                self.fn, stmt,
                f"`{target.id}` (from {self.sources.get(target.id)}) is "
                "rebound while still holding unwiped key material — wipe "
                "before reassigning"))
            self.reported.add(target.id)
            state[target.id] = _ESCAPED
        # storing the secret somewhere (attribute/subscript) = escape
        if not isinstance(target, ast.Name):
            self._mark_escapes(_names_in(stmt.value), state)

    def _wipes_in(self, root: ast.AST) -> set[str]:
        got: set[str] = set()
        for node in ast.walk(root):
            if not isinstance(node, ast.Call):
                continue
            leaf = last_attr(node.func) or ""
            if leaf in WIPERS:
                for a in node.args:
                    if isinstance(a, ast.Name):
                        got.add(a.id)
                if (isinstance(node.func, ast.Attribute)
                        and isinstance(node.func.value, ast.Name)):
                    got.add(node.func.value.id)
        return got

    def _scan_events(self, stmt: ast.stmt, state: dict[str, str]) -> None:
        for expr in _stmt_exprs(stmt):
            self._scan_events_expr(expr, state)

    def _scan_events_expr(self, expr: ast.AST, state: dict[str, str]) -> None:
        for name in self._wipes_in(expr):
            if name in state:
                state[name] = _WIPED
        for node in ast.walk(expr):
            if not isinstance(node, ast.Call):
                continue
            leaf = last_attr(node.func) or ""
            storing = (leaf in _STORING_METHODS
                       and isinstance(node.func, ast.Attribute))
            # a method on bare `self` delegates within the object — the
            # callee (also under this rule) owns the buffer from here on;
            # a plain function / other-object method does NOT discharge
            # (the KDF-pass case the rule exists for)
            self_method = (isinstance(node.func, ast.Attribute)
                           and isinstance(node.func.value, ast.Name)
                           and node.func.value.id == "self")
            if storing or self_method:
                for a in [*node.args, *[kw.value for kw in node.keywords]]:
                    if isinstance(a, ast.Name) and a.id in state:
                        state[a.id] = _ESCAPED
                    elif isinstance(a, (ast.Tuple, ast.List, ast.Set,
                                        ast.Dict)):
                        # out.append((pk, sk, sig)): the container owns it
                        for nm in _names_in(a):
                            if nm in state:
                                state[nm] = _ESCAPED
        # yields inside expression statements
        for node in ast.walk(expr):
            if isinstance(node, (ast.Yield, ast.YieldFrom)) and node.value:
                self._mark_escapes(_names_in(node.value), state)

    def _mark_escapes(self, names: set[str], state: dict[str, str]) -> None:
        for name in names:
            if name in state and state[name] == _LIVE:
                state[name] = _ESCAPED

    def _check_exit(self, state: dict[str, str], node: ast.AST,
                    how: str) -> None:
        covered = set().union(*self.finally_wiped) if self.finally_wiped else set()
        for name, st in sorted(state.items()):
            if st != _LIVE or name in covered or name in self.reported:
                continue
            self.reported.add(name)
            self.out.append(WipeGap(
                self.fn, node if hasattr(node, "lineno") else self.fn.node,
                f"`{name}` (from {self.sources.get(name, 'a SECRET source')}) "
                f"does not reach _wipe()/zeroize() where {self.fn.qualname}() "
                f"{how} — wipe it on every exit path or transfer ownership"))


def _merge(a: dict[str, str] | None,
           b: dict[str, str] | None) -> dict[str, str] | None:
    if a is None:
        return b
    if b is None:
        return a
    out: dict[str, str] = {}
    for name in a.keys() | b.keys():
        sa, sb = a.get(name), b.get(name)
        if sa == _LIVE or sb == _LIVE:
            out[name] = _LIVE
        else:
            out[name] = sa or sb  # type: ignore[assignment]
    return out


def _stmt_exprs(stmt: ast.stmt):
    for field, value in ast.iter_fields(stmt):
        if field in ("body", "orelse", "finalbody", "handlers"):
            continue
        if isinstance(value, ast.expr):
            yield value
        elif isinstance(value, list):
            for v in value:
                if isinstance(v, ast.expr):
                    yield v


def run_wipes(cg: CallGraph) -> list[WipeGap]:
    out: list[WipeGap] = []
    for mod in cg.modules.values():
        if any(frag in mod.path for frag in WIPE_EXCLUDE):
            continue
        for fn in walk_functions(mod):
            if fn.name in WIPERS or fn.name in SECRET_CALLS:
                continue
            _FnWipeScan(fn, out).run()
    return out
