"""qrlife — lock-discipline & resource-lifetime verifier.

The fifth analyzer of the qr-analysis ratchet (qrlint → qrflow →
qrkernel → qrproto → qrlife).  Pure AST on the qrlint engine, reusing
qrflow's call graph and ownership domains: builds the project-wide
lock-acquisition order graph, proves acquire/release pairing for the
resources the fleet actually leaks (subprocess spawns, StreamWriters,
executors, telemetry servers, tempdirs, tasks), and checks that every
SECRET-taint local reaches a wipe on every explicit exit path.
``python -m tools.analysis.life.run`` or the ``qrlife`` console script.
"""

from __future__ import annotations

from ..engine import Rule
from .packs import LIFE_RULES


def life_rules() -> list[Rule]:
    """Fresh instances of every qrlife rule (the all.py driver and the
    CLI both construct per-run rule objects, mirroring flow/kernel/proto)."""
    return [cls() for cls in LIFE_RULES]
