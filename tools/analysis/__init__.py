"""qrlint — crypto/JAX/asyncio-aware static analysis for quantum_resistant_p2p_tpu.

Generic linters cannot see this codebase's three domain-specific failure
modes: silent int32 overflow inside Pallas NTT arithmetic, swallowed
exceptions on fire-and-forget asyncio tasks, and secret material leaking
into logs or reprs.  qrlint is a small AST rule engine (engine.py) plus four
rule packs:

* rules_secret   — secret-hygiene (no secrets into logging/exceptions/repr;
                   zeroize methods must clear every secret-holding attribute)
* rules_jax      — jax-kernel discipline (no Python control flow on traced
                   values, no silently-narrowing int32 multiplies/shifts in
                   kernel arithmetic, no host<->device sync inside jit)
* rules_asyncio  — asyncio discipline (no dangling tasks, no unawaited
                   coroutines, no blocking calls in async defs, no silent
                   broad excepts)
* rules_provider — provider-contract (every registered algorithm implements
                   the full provider/base.py surface with matching batch
                   signatures)

Run: ``python -m tools.analysis.run quantum_resistant_p2p_tpu`` (or the
``qrlint`` console script).  Docs: docs/static_analysis.md.

The ``flow`` subpackage (**qrflow**) is the whole-program half built on
this engine: an interprocedural secret-taint / constant-time analysis and
a cross-thread shared-state race detector, run as a second CI ratchet —
``python -m tools.analysis.flow.run quantum_resistant_p2p_tpu``.

The ``kernel`` subpackage (**qrkernel**) is the device-side half: an
abstract-interpretation verifier for the JAX/Pallas kernel layer
(bit-width proofs that replaced the hand-justified int32-narrowing
suppressions, symbolic shape/batch-axis checks, pallas_call structure,
donation/recompile hazards), run as the third ratchet —
``python -m tools.analysis.kernel.run quantum_resistant_p2p_tpu``.
``python -m tools.analysis.all`` (``qr-analysis``) drives all three with
one merged SARIF, one exit code, and the suppression-count budget.
"""

from __future__ import annotations

from .engine import Engine, Finding, Rule  # noqa: F401


def default_rules() -> list[Rule]:
    """All four rule packs, instantiated fresh (rules keep per-run state)."""
    from .rules_asyncio import ASYNCIO_RULES
    from .rules_jax import JAX_RULES
    from .rules_provider import PROVIDER_RULES
    from .rules_secret import SECRET_RULES

    return [
        cls()
        for cls in (*SECRET_RULES, *JAX_RULES, *ASYNCIO_RULES, *PROVIDER_RULES)
    ]
