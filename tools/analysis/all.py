"""Unified analysis driver — ``python -m tools.analysis.all <targets>``.

Runs all five ratchets in order (qrlint → qrflow → qrkernel → qrproto →
qrlife) over the same targets, emits ONE merged SARIF document (one
``runs[]`` entry per analyzer) and returns ONE exit code, so CI needs a
single step instead of five.  Also asserts the **suppression budget**
(``tools/analysis/suppression_budget.json``): per-analyzer counts of
inline suppressions may only go DOWN — a PR that adds an unbudgeted
suppression fails loudly with the exact locations, and a PR that removes
one is told to ratchet the budget file.

Exit status: 0 all analyzers clean and within budget, 1 any error-severity
finding or budget overrun, 2 usage errors.

```
python -m tools.analysis.all quantum_resistant_p2p_tpu           # all five
qr-analysis quantum_resistant_p2p_tpu --sarif-out merged.sarif   # CI step
qr-analysis quantum_resistant_p2p_tpu --update-budget            # re-pin
```
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path

from . import default_rules
from .engine import Engine, Finding, resolve_target
from .flow import flow_rules
from .flow.sarif import to_sarif
from .kernel import kernel_rules
from .life import life_rules
from .proto import proto_rules

BUDGET_PATH = Path(__file__).resolve().parent / "suppression_budget.json"

#: (name, rule factory) in ratchet order
ANALYZERS = (
    ("qrlint", default_rules),
    ("qrflow", flow_rules),
    ("qrkernel", kernel_rules),
    ("qrproto", proto_rules),
    ("qrlife", life_rules),
)


def _resolve_target(target: str) -> Path:
    return resolve_target(target, "qr-analysis")


def run_all(targets: list[Path]) -> dict[str, tuple[list[Finding], list[Finding], list]]:
    """{analyzer: (findings, suppressed, rules)} over the shared targets."""
    out = {}
    for name, factory in ANALYZERS:
        rules = factory()
        findings, suppressed = Engine(rules).lint_paths(targets)
        out[name] = (findings, suppressed, rules)
    return out


def merged_sarif(results) -> dict:
    doc = None
    for name, (findings, suppressed, rules) in results.items():
        one = to_sarif(findings, suppressed, rules, tool_name=name)
        if doc is None:
            doc = one
        else:
            doc["runs"].extend(one["runs"])
    return doc or {"version": "2.1.0", "runs": []}


def check_budget(results, budget: dict) -> list[str]:
    """Budget violations (empty = counts EQUAL the budget).

    The budget is an equality pin, which is what makes it a one-way
    ratchet: an overrun means an unbudgeted suppression was added (fix the
    finding, or raise the pin with explicit reviewer sign-off); an
    *underrun* means suppressions were removed without re-pinning — the PR
    must run ``--update-budget`` so the headroom can't silently creep back.
    """
    problems = []
    for name, (_findings, suppressed, _rules) in results.items():
        allowed = budget.get(name)
        if allowed is None:
            problems.append(f"{name}: no budget entry — add one to "
                            f"{BUDGET_PATH.name} (current count: {len(suppressed)})")
            continue
        if len(suppressed) > allowed:
            lines = [f"{name}: {len(suppressed)} suppressions > budget {allowed} "
                     "— fix the finding instead of waiving it, or (with "
                     "reviewer sign-off) raise the budget explicitly:"]
            for s in suppressed:
                lines.append(f"    {s.path}:{s.line}: [{s.rule}]")
            problems.append("\n".join(lines))
        elif len(suppressed) < allowed:
            problems.append(
                f"{name}: {len(suppressed)} suppressions < budget {allowed} "
                "— you removed one (nice): re-pin the ratchet with "
                "`qr-analysis --update-budget` so the headroom can't be "
                "spent by a later PR")
    return problems


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="qr-analysis",
        description=("unified static-analysis driver: qrlint + qrflow + "
                     "qrkernel + qrproto + qrlife, one exit code, one "
                     "merged SARIF (docs/static_analysis.md)"),
    )
    ap.add_argument("targets", nargs="*", default=["quantum_resistant_p2p_tpu"],
                    help="files, directories, or package names (default: the package)")
    ap.add_argument("--format", choices=("human", "json", "sarif"),
                    default="human", help="output format (default: human)")
    ap.add_argument("--sarif-out", metavar="FILE",
                    help="also write the merged SARIF document to FILE")
    ap.add_argument("--no-budget", action="store_true",
                    help="skip the suppression-budget assertion")
    ap.add_argument("--update-budget", action="store_true",
                    help="re-pin suppression_budget.json to the current "
                         "counts (use after deliberately removing one)")
    args = ap.parse_args(argv)

    targets = [_resolve_target(t) for t in (args.targets or ["quantum_resistant_p2p_tpu"])]
    results = run_all(targets)

    if args.sarif_out or args.format == "sarif":
        doc = merged_sarif(results)
        if args.sarif_out:
            Path(args.sarif_out).write_text(json.dumps(doc, indent=2),
                                            encoding="utf-8")
        if args.format == "sarif":
            print(json.dumps(doc, indent=2))

    budget_problems: list[str] = []
    default_target = args.targets in ([], ["quantum_resistant_p2p_tpu"])
    if args.update_budget:
        budget = {name: len(suppressed)
                  for name, (_f, suppressed, _r) in results.items()}
        BUDGET_PATH.write_text(json.dumps(budget, indent=2) + "\n",
                               encoding="utf-8")
        print(f"qr-analysis: budget re-pinned: {budget}")
    elif not args.no_budget and default_target:
        if BUDGET_PATH.is_file():
            budget = json.loads(BUDGET_PATH.read_text(encoding="utf-8"))
            budget_problems = check_budget(results, budget)
        else:
            # never skip the ratchet silently (e.g. a wheel install that
            # dropped the json): missing budget is itself a violation
            budget_problems = [
                f"budget file missing: {BUDGET_PATH} — re-create it with "
                "`qr-analysis --update-budget` (or pass --no-budget to "
                "run without the ratchet)"]

    any_errors = False
    if args.format == "json":
        payload = {}
        for name, (findings, suppressed, _rules) in results.items():
            payload[name] = {
                "findings": [f.as_dict() for f in findings],
                "suppressed": [s.as_dict() for s in suppressed],
            }
        payload["budget_violations"] = budget_problems
        print(json.dumps(payload, indent=2))
    elif args.format == "human":
        for name, (findings, suppressed, _rules) in results.items():
            for f in findings:
                print(f.format())
            errs = sum(f.severity == "error" for f in findings)
            print(f"{name}: {errs} error(s), "
                  f"{sum(f.severity == 'warning' for f in findings)} warning(s), "
                  f"{len(suppressed)} suppressed")
    for name, (findings, _s, _r) in results.items():
        if any(f.severity == "error" for f in findings):
            any_errors = True
    for problem in budget_problems:
        print(f"qr-analysis: suppression budget violation:\n  {problem}",
              file=sys.stderr)
    if budget_problems:
        any_errors = True
    return 1 if any_errors else 0


if __name__ == "__main__":
    sys.exit(main())
