"""Secret-hygiene rule pack.

The storage layer holds long-lived secret material (vault master keys,
per-peer shared secrets, signature secret keys).  Two failure modes this
pack catches:

* ``secret-in-log`` — a secret-named value flowing into a logging call, an
  exception message, a ``repr()``, or an ``{x!r}`` f-string conversion.
  Audit-log sinks (``log_event`` / ``_log``) count as logging: the audit log
  is encrypted, but its queries are displayed in cleartext (cli.py /logs).
* ``zeroize-incomplete`` — a class that CLAIMS zeroization (defines
  ``zeroize``/``_zeroize``) but forgets to clear one of its secret-holding
  attributes, silently extending key lifetime (storage/key_storage.py's
  lock() contract).
"""

from __future__ import annotations

import ast

from .engine import FileContext, Rule, call_name, last_attr

# the secret-name vocabulary is shared with the RUNTIME redactor
# (obs/flight.py) — one module, imported by both sides, so static rules
# and record-time redaction can never disagree on what "secret" means
from quantum_resistant_p2p_tpu.obs.redaction import (  # noqa: F401  (re-export)
    NONSECRET_NAME_RE,
    SECRET_NAME_RE,
    is_secret_name,
)

#: method names treated as logging sinks.  log_event/_log are this repo's
#: encrypted audit-log writers — decrypted and displayed by /logs.
LOG_METHODS = {"debug", "info", "warning", "error", "exception", "critical",
               "log", "log_event", "_log"}


#: calls whose result no longer reveals the secret (sizes, types, hashes of
#: public data are fine to log)
_SANITIZERS = {"len", "type", "bool", "id"}


def secret_refs(node: ast.AST) -> list[ast.AST]:
    """Secret-named Name/Attribute nodes reachable in ``node``, skipping
    subtrees wrapped in a sanitizing call (``len(secret)`` is loggable)."""
    out: list[ast.AST] = []

    def visit(n: ast.AST) -> None:
        if isinstance(n, ast.Call):
            fname = call_name(n)
            if fname and fname.split(".")[-1] in _SANITIZERS:
                return  # sanitized: do not descend into the arguments
        if isinstance(n, (ast.Name, ast.Attribute)) and is_secret_name(last_attr(n)):
            out.append(n)
            return  # the chain itself is the finding; don't double-report
        for child in ast.iter_child_nodes(n):
            visit(child)

    visit(node)
    return out


def _is_logging_call(call: ast.Call) -> bool:
    func = call.func
    if not isinstance(func, ast.Attribute) or func.attr not in LOG_METHODS:
        return False
    receiver = last_attr(func.value)
    if func.attr in ("log_event", "_log"):
        return True
    # logger.info(...), logging.warning(...), self.logger.error(...)
    return bool(receiver) and ("log" in receiver.lower() or receiver == "logging")


class SecretInLogRule(Rule):
    id = "secret-in-log"
    description = (
        "secret-named value flows into a logging call, exception message, "
        "repr(), or {x!r} f-string"
    )

    def start_file(self, ctx: FileContext):
        return {
            ast.Call: lambda n: self._call(ctx, n),
            ast.Raise: lambda n: self._raise(ctx, n),
            ast.FormattedValue: lambda n: self._fvalue(ctx, n),
        }

    def _call(self, ctx: FileContext, node: ast.Call) -> None:
        if isinstance(node.func, ast.Name) and node.func.id == "repr":
            for arg in node.args:
                for ref in secret_refs(arg):
                    ctx.report(self, node,
                               f"repr() of secret {last_attr(ref)!r} exposes key material")
            return
        if not _is_logging_call(node):
            return
        for arg in [*node.args, *[kw.value for kw in node.keywords]]:
            for ref in secret_refs(arg):
                ctx.report(
                    self, ref,
                    f"secret {last_attr(ref)!r} passed to logging sink "
                    f"{call_name(node) or node.func.attr!r}",
                )

    def _raise(self, ctx: FileContext, node: ast.Raise) -> None:
        if not isinstance(node.exc, ast.Call):
            return
        for arg in node.exc.args:
            for ref in secret_refs(arg):
                ctx.report(
                    self, ref,
                    f"secret {last_attr(ref)!r} embedded in exception message "
                    "(exceptions end up in logs and tracebacks)",
                )

    def _fvalue(self, ctx: FileContext, node: ast.FormattedValue) -> None:
        # {secret!r} in any f-string: the repr goes wherever the string goes.
        if node.conversion == ord("r"):
            for ref in secret_refs(node.value):
                ctx.report(self, ref,
                           f"{{{last_attr(ref)}!r}} formats secret material")


class ZeroizeIncompleteRule(Rule):
    id = "zeroize-incomplete"
    description = (
        "class defines zeroize()/_zeroize() but does not clear every "
        "secret-holding attribute it assigns"
    )

    _ZEROIZE_NAMES = {"zeroize", "_zeroize"}

    def start_file(self, ctx: FileContext):
        return {ast.ClassDef: lambda n: self._check_class(ctx, n)}

    def _check_class(self, ctx: FileContext, cls: ast.ClassDef) -> None:
        zeroize = next(
            (
                n for n in cls.body
                if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
                and n.name in self._ZEROIZE_NAMES
            ),
            None,
        )
        if zeroize is None:
            return  # no zeroization claim, nothing to verify
        secret_attrs = self._secret_attrs(cls)
        cleared = {
            t.attr
            for stmt in ast.walk(zeroize)
            if isinstance(stmt, ast.Assign)
            for t in stmt.targets
            if isinstance(t, ast.Attribute)
            and isinstance(t.value, ast.Name) and t.value.id == "self"
        }
        missing = sorted(secret_attrs - cleared)
        if missing:
            ctx.report(
                self, zeroize,
                f"{cls.name}.{zeroize.name}() does not clear secret "
                f"attribute(s): {', '.join(missing)}",
            )

    def _secret_attrs(self, cls: ast.ClassDef) -> set[str]:
        """Attributes that are secret by NAME or assigned FROM a secret-named
        value (``self._aead = AESGCM(key)`` holds the key even though the
        attribute name doesn't say so)."""
        out: set[str] = set()
        for node in ast.walk(cls):
            if not isinstance(node, ast.Assign):
                continue
            for t in node.targets:
                if not (
                    isinstance(t, ast.Attribute)
                    and isinstance(t.value, ast.Name)
                    and t.value.id == "self"
                ):
                    continue
                if is_secret_name(t.attr) and not _is_cleared_value(node.value):
                    out.add(t.attr)
                elif secret_refs(node.value):
                    out.add(t.attr)
        return out


def _is_cleared_value(value: ast.AST) -> bool:
    """``None`` / ``b""`` / ``0`` assignments are clears, not holdings."""
    return isinstance(value, ast.Constant) and not value.value


SECRET_RULES = (SecretInLogRule, ZeroizeIncompleteRule)
