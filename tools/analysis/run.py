"""qrlint CLI — ``python -m tools.analysis.run <package-or-path>``.

Exit status is the CI ratchet: 0 when the tree is clean (modulo explicit,
justified suppressions), 1 when any error-severity finding remains, 2 on
usage errors.  ``--json`` emits machine-readable output for tooling.
"""

from __future__ import annotations

import argparse
import sys
from pathlib import Path

from . import default_rules
from .engine import Engine, render_findings, resolve_target


def _resolve_target(target: str) -> Path:
    return resolve_target(target, "qrlint")


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="qrlint",
        description="crypto/JAX/asyncio-aware static analysis (docs/static_analysis.md)",
    )
    ap.add_argument("targets", nargs="*", default=["quantum_resistant_p2p_tpu"],
                    help="files, directories, or package names (default: the package)")
    ap.add_argument("--json", action="store_true", help="machine-readable output")
    ap.add_argument("--select", help="comma-separated rule ids to run (default: all)")
    ap.add_argument("--ignore", help="comma-separated rule ids to skip")
    ap.add_argument("--list-rules", action="store_true", help="list rules and exit")
    args = ap.parse_args(argv)

    rules = default_rules()
    if args.list_rules:
        for rule in rules:
            print(f"{rule.id:18} [{rule.severity}] {rule.description}")
        return 0
    if args.select:
        wanted = {r.strip() for r in args.select.split(",")}
        unknown = wanted - {r.id for r in rules}
        if unknown:
            print(f"qrlint: unknown rule id(s): {', '.join(sorted(unknown))}",
                  file=sys.stderr)
            return 2
        rules = [r for r in rules if r.id in wanted]
    if args.ignore:
        dropped = {r.strip() for r in args.ignore.split(",")}
        rules = [r for r in rules if r.id not in dropped]

    targets = [_resolve_target(t) for t in (args.targets or ["quantum_resistant_p2p_tpu"])]
    findings, suppressed = Engine(rules).lint_paths(targets)
    out = render_findings(findings, suppressed, as_json=args.json)
    if out:
        print(out)
    return 1 if any(f.severity == "error" for f in findings) else 0


if __name__ == "__main__":
    sys.exit(main())
