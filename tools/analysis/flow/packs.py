"""qrflow analysis packs, exposed as qrlint ``Rule`` objects.

One :class:`FlowAnalysis` is computed per project run (call graph ->
taint fixpoint -> domain inference -> write-site collection) and cached
on the ``Project``; the thin rule classes below each publish their own
finding id from it, so ``--select``/``--ignore`` and the inline
``# qrlint: disable=`` suppression machinery work unchanged.

Rule ids:

======================  =====================================================
flow-secret-in-log      tainted value reaches a logging / audit-log call
flow-secret-in-exception tainted value embedded in an exception message
flow-secret-format      repr()/str()/f-string renders a tainted value
flow-secret-to-network  tainted value reaches a network send before AEAD
                        (peer frames, or the HTTP telemetry ``_respond``
                        response surface)
flow-secret-in-trace    tainted value reaches an observability sink (span
                        attribute, metric label, flight-recorder payload)
flow-secret-compare     ==/!= on key material (use hmac.compare_digest)
flow-secret-branch      secret-dependent branch / secret-indexed lookup
cross-thread-state      attribute written from two ownership domains unlocked
asyncio-off-loop        non-threadsafe loop API called from a thread domain
unjustified-suppression a qrflow suppression with no one-line justification
======================  =====================================================

Scope policy for the constant-time rules (``flow-secret-compare`` /
``flow-secret-branch``): paths under ``pyref/`` are excluded by default —
they are pure-Python FIPS references where arithmetic on secret
polynomials IS the algorithm and no production traffic runs through them;
the jax providers that do serve traffic are branch-free on secrets by
construction (qrlint's ``traced-branch`` forbids Python control flow on
traced values).  Pass ``ct_all=True`` (CLI ``--ct-all``) to lift the
exclusion for an audit sweep.
"""

from __future__ import annotations

import re

from ..engine import FileContext, Project, Rule
from .callgraph import build_callgraph
from .domains import (collect_off_loop_calls, collect_write_sites,
                      infer_domains)
from .taint import SinkHit, TaintEngine

#: constant-time rules skip these path fragments by default (see module doc)
CT_EXCLUDE = ("pyref/", "pyref\\")
CT_RULES = ("flow-secret-compare", "flow-secret-branch")

#: process-wide default for lifting the CT_EXCLUDE scope (set by the CLI's
#: ``--ct-all``; a module flag because rules are constructed by the engine
#: without CLI context)
CT_ALL = False

# every prefix: the engine accepts `# qrkernel: disable=…` and
# `# qrproto: disable=…` too, so a flow rule suppressed through THOSE
# spellings must be policed all the same
_SUPPRESS_RE = re.compile(
    r"#\s*(?:qrlint|qrkernel|qrproto|qrlife):\s*disable(?:-file)?\s*=\s*"
    r"(?P<rules>[\w.,\- ]+)(?P<rest>.*)$")


class FlowAnalysis:
    """All qrflow findings for one project, computed once and cached."""

    def __init__(self, project: Project, ct_all: bool = False):
        self.project = project
        self.cg = build_callgraph(project)
        self.findings: list[tuple[str, FileContext, object, str]] = []
        self._run_taint(ct_all)
        self._run_races()

    @classmethod
    def of(cls, project: Project, ct_all: bool | None = None) -> "FlowAnalysis":
        cached = getattr(project, "_qrflow_analysis", None)
        if cached is None:
            cached = cls(project, ct_all=CT_ALL if ct_all is None else ct_all)
            project._qrflow_analysis = cached  # type: ignore[attr-defined]
        return cached

    def _add(self, rule_id: str, ctx: FileContext, node, message: str) -> None:
        self.findings.append((rule_id, ctx, node, message))

    # -- taint ----------------------------------------------------------------

    def _run_taint(self, ct_all: bool) -> None:
        engine = TaintEngine(self.cg)
        engine.solve()
        self.taint_engine = engine
        seen: set[tuple[str, str, int, int]] = set()

        def report(hit: SinkHit) -> None:
            if hit.rule in CT_RULES and not ct_all and any(
                    frag in hit.fn.path for frag in CT_EXCLUDE):
                return
            key = (hit.rule, hit.fn.path,
                   getattr(hit.node, "lineno", 0),
                   getattr(hit.node, "col_offset", 0))
            if key in seen:
                return
            seen.add(key)
            self._add(hit.rule, hit.fn.ctx, hit.node,
                      f"{hit.message} [in {hit.fn.qualname}]")

        engine.report_pass(lambda fn: True, report)

    # -- races ----------------------------------------------------------------

    def _run_races(self) -> None:
        domains = infer_domains(self.cg)
        self.domains = domains
        sites = collect_write_sites(self.cg)
        by_attr: dict[tuple[str, str], list] = {}
        for site in sites:
            by_attr.setdefault((site.cls, site.attr), []).append(site)
        for (cls, attr), group in sorted(by_attr.items()):
            all_domains: set[str] = set()
            for site in group:
                all_domains |= {
                    d for d in domains.get(site.fn.fid, set())
                    if d == "loop" or d == "executor" or d.startswith("thread")
                }
            if len(all_domains) < 2:
                continue
            unguarded = [s for s in group if not s.locked]
            if not unguarded:
                continue
            site = unguarded[0]
            writers = sorted({s.fn.qualname for s in group})
            self._add(
                "cross-thread-state", site.fn.ctx, site.node,
                f"{cls}.{attr} is written from multiple ownership domains "
                f"({', '.join(sorted(all_domains))}) by "
                f"{', '.join(writers[:4])}"
                f"{'…' if len(writers) > 4 else ''} with at least one write "
                "not lock-guarded; add a lock or hand off via "
                "call_soon_threadsafe",
            )
        for call in collect_off_loop_calls(self.cg, domains):
            owned = sorted(domains.get(call.fn.fid, set()))
            self._add(
                "asyncio-off-loop", call.fn.ctx, call.node,
                f"{call.api}() called from {call.fn.qualname}, which runs in "
                f"domain(s) {', '.join(owned)}: event-loop APIs are not "
                "thread-safe off-loop; use call_soon_threadsafe / "
                "run_coroutine_threadsafe",
            )


class _FlowRule(Rule):
    """Base: publish one finding id out of the shared analysis."""

    severity = "error"

    def check_project(self, project: Project) -> None:
        analysis = FlowAnalysis.of(project)
        for rule_id, ctx, node, message in analysis.findings:
            if rule_id == self.id:
                project.report(self, ctx, node, message)


class SecretInLogFlowRule(_FlowRule):
    id = "flow-secret-in-log"
    description = ("interprocedural: key material (decaps output, secret key, "
                   "HKDF output) reaches a logging or audit-log call")


class SecretInExceptionFlowRule(_FlowRule):
    id = "flow-secret-in-exception"
    description = "interprocedural: key material embedded in an exception message"


class SecretFormatFlowRule(_FlowRule):
    id = "flow-secret-format"
    description = "repr()/str()/f-string renders interprocedurally-tainted key material"


class SecretToNetworkFlowRule(_FlowRule):
    id = "flow-secret-to-network"
    description = ("key material reaches a network send before AEAD "
                   "encryption — peer frames (send_message/sendall/sendto) "
                   "or the HTTP telemetry response surface (obs/http.py "
                   "_respond: scraped bodies must be built only from "
                   "registry snapshots / SLO reports / span dumps)")


class SecretInTraceFlowRule(_FlowRule):
    id = "flow-secret-in-trace"
    description = ("key material reaches an observability sink — span "
                   "attributes, metric labels, flight-recorder payloads, and "
                   "the cross-peer wire-propagation surface (wire_context/"
                   "adopt_wire_context) are exported in cleartext "
                   "diagnostics or ride the network (obs/)")


class SecretCompareFlowRule(_FlowRule):
    id = "flow-secret-compare"
    description = ("==/!= on key material — variable-time comparison; "
                   "use hmac.compare_digest")


class SecretBranchFlowRule(_FlowRule):
    id = "flow-secret-branch"
    description = ("secret-dependent if/while or secret-indexed table lookup "
                   "— branch/cache timing side channel")


class CrossThreadStateRule(_FlowRule):
    id = "cross-thread-state"
    description = ("attribute written from two ownership domains (event loop "
                   "/ warmup thread / executor) without a lock")


class AsyncioOffLoopRule(_FlowRule):
    id = "asyncio-off-loop"
    description = ("non-threadsafe asyncio API called from a thread/executor "
                   "ownership domain")


class UnjustifiedSuppressionRule(Rule):
    """Suppressing a qrflow finding requires a one-line justification after
    the rule ids (separated by a non-word character, e.g. ``—``) — the same
    convention docs/static_analysis.md mandates for qrlint, here enforced."""

    id = "unjustified-suppression"
    severity = "error"
    description = ("a qrflow suppression comment carries no one-line "
                   "justification after the rule id(s)")

    #: ids whose suppressions this rule polices (its own id included so a
    #: suppression of THIS rule also needs a reason)
    _POLICED: frozenset[str] = frozenset({
        "flow-secret-in-log", "flow-secret-in-exception", "flow-secret-format",
        "flow-secret-to-network", "flow-secret-in-trace", "flow-secret-compare",
        "flow-secret-branch", "cross-thread-state", "asyncio-off-loop",
        "unjustified-suppression",
    })

    def check_project(self, project: Project) -> None:
        for ctx in project.contexts.values():
            for lineno, line in enumerate(ctx.lines, start=1):
                m = _SUPPRESS_RE.search(line)
                if not m:
                    continue
                blob = m.group("rules")
                rest = m.group("rest") or ""
                # ids run up to the first non-[word,space,comma,dash] char;
                # everything after that separator is the justification
                sep = re.search(r"[^\w,\- ]", blob)
                ids_part = blob[: sep.start()] if sep else blob
                justification = (blob[sep.start():] if sep else "") + rest
                ids = {tok for part in ids_part.split(",")
                       for tok in part.strip().split() if tok}
                flow_ids = ids & self._POLICED
                if flow_ids and not re.search(r"\w", justification):
                    node = _LineNode(lineno)
                    project.report(
                        self, ctx, node,
                        f"suppression of {', '.join(sorted(flow_ids))} has no "
                        "justification — append one after the rule id "
                        "(e.g. `# qrlint: disable=flow-secret-compare — "
                        "probe-only ephemeral key`)",
                    )


class _LineNode:
    """Minimal AST-node stand-in so line-anchored findings route through
    the normal report/suppression machinery."""

    def __init__(self, lineno: int):
        self.lineno = lineno
        self.end_lineno = lineno
        self.col_offset = 0


FLOW_RULES = (
    SecretInLogFlowRule, SecretInExceptionFlowRule, SecretFormatFlowRule,
    SecretToNetworkFlowRule, SecretInTraceFlowRule, SecretCompareFlowRule,
    SecretBranchFlowRule, CrossThreadStateRule, AsyncioOffLoopRule,
    UnjustifiedSuppressionRule,
)
