"""Ownership-domain inference and the cross-thread race pack's raw data.

Every function gets a set of OWNERSHIP DOMAINS — execution contexts its
body can run in:

* ``loop``       — the asyncio event loop: async defs, loop callbacks
  (``call_soon``/``call_later``), asyncio-future done-callbacks, tasks.
* ``thread:<n>`` — a named ``threading.Thread`` target (and everything it
  calls): e.g. ``thread:qrp2p-warmup`` for the background warmup.
* ``subprocess`` — a ``python -m`` worker module's entry point (the
  fleet gateway spawn): its own process, so it can never race the
  manager — seeded for reachability/ownership attribution only.
* ``executor``   — callables submitted to a ThreadPoolExecutor
  (``run_in_executor`` / ``.submit``) and their transitive callees, plus
  callables handed to the sharded crypto plane's placement boundary
  (``Shard.run_placed``, provider/scheduler.py) — a placed device program
  runs on a dispatch worker under the shard's placement context, so a
  placement call IS a cross-thread edge.

Domains propagate along plain call/await edges to a fixpoint: a sync
helper called from both a coroutine and a thread target ends up owning
``{loop, thread:...}`` — which is exactly the signature of shared state.

On top of the domains, every ATTRIBUTE WRITE SITE is collected — direct
assignments (``self.x = v``, ``obj.x += v``) and container mutation
through a method (``obj.attr.add(v)``, ``self.stats.record(...)``) — with
its receiver class resolved by the call graph's type machinery (falling
back to the project-unique class that assigns that attribute name).
Writes inside ``__init__``/``__post_init__`` are construction, not
sharing, and are excluded; writes under a ``with <...lock...>:`` block
are marked lock-guarded.

packs.py turns this into findings:

* ``cross-thread-state`` — one (class, attribute) written from two
  different domains (or from one function owned by two domains) with at
  least one write not lock-guarded: a data race unless a documented
  handoff exists.
* ``asyncio-off-loop``   — a non-threadsafe event-loop API
  (``create_task``/``ensure_future``/``call_soon``/``call_later``/
  ``call_at``) invoked from a function owned by a thread/executor
  domain; use ``call_soon_threadsafe`` / ``run_coroutine_threadsafe``.
"""

from __future__ import annotations

import ast
import dataclasses

from ..engine import dotted_name, last_attr
from .callgraph import MUTATORS, CallGraph, FunctionInfo

#: loop APIs that are NOT safe to call from another thread (their
#: threadsafe twins are fine and excluded by name)
OFF_LOOP_APIS = {"create_task", "ensure_future", "call_soon", "call_later",
                 "call_at"}

PROPAGATE_KINDS = ("call", "await")


def infer_domains(cg: CallGraph) -> dict[str, set[str]]:
    domains: dict[str, set[str]] = {fid: set() for fid in cg.functions}
    for fid, fn in cg.functions.items():
        if fn.is_async:
            domains[fid].add("loop")
    for site in cg.edges:
        if site.kind == "thread":
            domains[site.callee.fid].add(site.label or "thread")
        elif site.kind == "executor":
            domains[site.callee.fid].add("executor")
        elif site.kind in ("loop_cb", "task"):
            domains[site.callee.fid].add("loop")
        elif site.kind == "subprocess":
            # a spawned gateway worker runs in its OWN process: its state
            # can never race the manager's, but the edge keeps the worker
            # reachable/attributed for the dead-code and ownership views
            domains[site.callee.fid].add("subprocess")
    changed = True
    while changed:
        changed = False
        for site in cg.edges:
            if site.kind not in PROPAGATE_KINDS:
                continue
            src = domains[site.caller.fid]
            dst = domains[site.callee.fid]
            if src - dst:
                dst |= src
                changed = True
    return domains


@dataclasses.dataclass
class WriteSite:
    cls: str
    attr: str
    fn: FunctionInfo
    node: ast.AST
    locked: bool
    kind: str   # "assign" | "mutate"


def _is_lock_expr(node: ast.AST) -> bool:
    name = (dotted_name(node) or last_attr(node) or "").lower()
    if "lock" in name:
        return True
    if isinstance(node, ast.Call):
        return _is_lock_expr(node.func)
    return False


class _AttrIndex:
    """attr name -> classes that assign it (for receiver-class fallback)."""

    def __init__(self, cg: CallGraph):
        self.by_attr: dict[str, set[str]] = {}
        for cls in cg.classes.values():
            for attr in cls.attrs:
                self.by_attr.setdefault(attr, set()).add(cls.name)

    def unique_owner(self, attr: str) -> str | None:
        owners = self.by_attr.get(attr, set())
        return next(iter(owners)) if len(owners) == 1 else None


def collect_write_sites(cg: CallGraph) -> list[WriteSite]:
    out: list[WriteSite] = []
    attr_index = _AttrIndex(cg)
    for fid, fn in cg.functions.items():
        if fn.is_init:
            continue
        local_types = getattr(fn, "_local_types", {})
        cls_attr = cg.class_attr_types.get(fn.class_name or "", {})

        def receiver_classes(recv: ast.AST, attr: str) -> list[str]:
            if isinstance(recv, ast.Name):
                if recv.id == "self" and fn.class_name is not None:
                    return [fn.class_name]
                types = cg._lookup_types(recv.id, fn, local_types)
                if types:
                    return sorted(types)
            if (isinstance(recv, ast.Attribute)
                    and isinstance(recv.value, ast.Name)
                    and recv.value.id == "self"):
                types = cls_attr.get(recv.attr, set())
                if types:
                    return sorted(types)
            owner = attr_index.unique_owner(attr)
            return [owner] if owner is not None else []

        def record(recv: ast.AST, attr: str, node: ast.AST, locked: bool,
                   kind: str) -> None:
            for cls in receiver_classes(recv, attr):
                if cls in cg.classes and attr in cg.classes[cls].attrs:
                    out.append(WriteSite(cls, attr, fn, node, locked, kind))

        def walk(node: ast.AST, locked: bool) -> None:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda)):
                return
            if isinstance(node, (ast.With, ast.AsyncWith)):
                now_locked = locked or any(
                    _is_lock_expr(item.context_expr) for item in node.items)
                for child in ast.iter_child_nodes(node):
                    walk(child, now_locked)
                return
            if isinstance(node, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
                targets = (node.targets if isinstance(node, ast.Assign)
                           else [node.target])
                for t in targets:
                    if isinstance(t, ast.Attribute):
                        record(t.value, t.attr, node, locked, "assign")
                    elif (isinstance(t, ast.Subscript)
                          and isinstance(t.value, ast.Attribute)):
                        record(t.value.value, t.value.attr, node, locked,
                               "mutate")
            if isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute):
                if (node.func.attr in MUTATORS
                        and isinstance(node.func.value, ast.Attribute)):
                    inner = node.func.value
                    record(inner.value, inner.attr, node, locked, "mutate")
            for child in ast.iter_child_nodes(node):
                walk(child, locked)

        for stmt in getattr(fn.node, "body", []):
            walk(stmt, False)
    return out


@dataclasses.dataclass
class OffLoopCall:
    fn: FunctionInfo
    node: ast.AST
    api: str


def collect_off_loop_calls(cg: CallGraph,
                           domains: dict[str, set[str]]) -> list[OffLoopCall]:
    out: list[OffLoopCall] = []
    for fid, fn in cg.functions.items():
        owned = domains.get(fid, set())
        if not any(d == "executor" or d.startswith("thread") for d in owned):
            continue

        def walk(node: ast.AST) -> None:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda)):
                return
            if isinstance(node, ast.Call):
                leaf = last_attr(node.func) or ""
                if leaf in OFF_LOOP_APIS:
                    out.append(OffLoopCall(fn, node, leaf))
            for child in ast.iter_child_nodes(node):
                walk(child)

        for stmt in getattr(fn.node, "body", []):
            walk(stmt)
    return out
