"""qrflow — interprocedural secret-taint / constant-time analysis and a
cross-thread shared-state race detector, layered on the qrlint engine.

qrlint (tools/analysis) is per-file and per-function: it cannot see a
decapsulated shared secret flowing through three call frames into a log
line, or an attribute mutated from both the warmup thread and the asyncio
event loop.  qrflow adds the whole-program half:

* callgraph.py — a project-wide call graph: name/attribute resolution
  through module imports, ``self`` method dispatch (including subclass
  overrides), ``functools.partial``, provider-registry dispatch
  (``get_kem``/``get_signature``/``get_fused`` calls resolve to every
  registered implementation), and async/await, thread-target, executor,
  and loop-callback edges.
* taint.py — a forward interprocedural taint analysis over a small
  lattice (PUBLIC < ZEROIZED < SECRET_DERIVED < SECRET) with per-function
  summaries computed to fixpoint (the summary cache keeps CI runs fast)
  and crypto-op models (keygen/encaps/decaps/sign/verify/AEAD) so
  signatures and ciphertexts stay public while shared secrets stay secret.
* domains.py — per-object ownership domains (event-loop-owned,
  thread-owned, executor-owned, lock-guarded) inferred from where
  attributes are written, feeding the race pack.
* packs.py — the two analysis packs as qrlint ``Rule`` objects:
  secret-flow / constant-time (``flow-secret-*``) and the cross-thread
  race pack (``cross-thread-state`` / ``asyncio-off-loop``), plus the
  suppression-justification ratchet (``unjustified-suppression``).
* sarif.py / run.py — human, JSON, and SARIF 2.1.0 output and the CLI:
  ``python -m tools.analysis.flow.run quantum_resistant_p2p_tpu`` (or the
  ``qrflow`` console script).

Suppression uses the same inline convention as qrlint
(``# qrlint: disable=rule-id — one-line justification``); qrflow
additionally REQUIRES the justification for its own rule ids.
Docs: docs/static_analysis.md (qrflow section).
"""

from __future__ import annotations

from ..engine import Rule  # noqa: F401  (re-export for rule authors)


def flow_rules() -> list[Rule]:
    """All qrflow rules, instantiated fresh (they share one cached
    analysis per project run)."""
    from .packs import FLOW_RULES

    return [cls() for cls in FLOW_RULES]
