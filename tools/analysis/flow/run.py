"""qrflow CLI — ``python -m tools.analysis.flow.run <package-or-path>``.

Exit status mirrors qrlint's ratchet contract: 0 when the tree is clean
(modulo explicit, JUSTIFIED suppressions), 1 when any error-severity
finding remains, 2 on usage errors.  ``--format json`` and ``--format
sarif`` emit machine-readable output (SARIF for code-scanning UIs).
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path

from ..engine import Engine, render_findings, resolve_target
from . import flow_rules
from .sarif import to_sarif


def _resolve_target(target: str) -> Path:
    return resolve_target(target, "qrflow")


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="qrflow",
        description=("interprocedural secret-taint / constant-time / "
                     "cross-thread-race analysis (docs/static_analysis.md)"),
    )
    ap.add_argument("targets", nargs="*", default=["quantum_resistant_p2p_tpu"],
                    help="files, directories, or package names (default: the package)")
    ap.add_argument("--format", choices=("human", "json", "sarif"),
                    default="human", help="output format (default: human)")
    ap.add_argument("--json", action="store_true",
                    help="alias for --format json (qrlint compatibility)")
    ap.add_argument("--select", help="comma-separated rule ids to run (default: all)")
    ap.add_argument("--ignore", help="comma-separated rule ids to skip")
    ap.add_argument("--list-rules", action="store_true", help="list rules and exit")
    ap.add_argument("--ct-all", action="store_true",
                    help="run the constant-time rules on pyref/ too "
                         "(audit sweep; excluded by default)")
    args = ap.parse_args(argv)

    from . import packs

    packs.CT_ALL = bool(args.ct_all)

    rules = flow_rules()
    if args.list_rules:
        for rule in rules:
            print(f"{rule.id:26} [{rule.severity}] {rule.description}")
        return 0
    if args.select:
        wanted = {r.strip() for r in args.select.split(",")}
        unknown = wanted - {r.id for r in rules}
        if unknown:
            print(f"qrflow: unknown rule id(s): {', '.join(sorted(unknown))}",
                  file=sys.stderr)
            return 2
        rules = [r for r in rules if r.id in wanted]
    if args.ignore:
        dropped = {r.strip() for r in args.ignore.split(",")}
        rules = [r for r in rules if r.id not in dropped]

    targets = [_resolve_target(t) for t in (args.targets or ["quantum_resistant_p2p_tpu"])]
    findings, suppressed = Engine(rules).lint_paths(targets)

    fmt = "json" if args.json else args.format
    if fmt == "sarif":
        print(json.dumps(to_sarif(findings, suppressed, rules), indent=2))
    else:
        out = render_findings(findings, suppressed, as_json=(fmt == "json"))
        if out and fmt == "human":
            # the summary trailer says "qrlint:"; rebrand ONLY that line
            lines = out.splitlines()
            lines[-1] = lines[-1].replace("qrlint:", "qrflow:", 1)
            out = "\n".join(lines)
        if out:
            print(out)
    return 1 if any(f.severity == "error" for f in findings) else 0


if __name__ == "__main__":
    sys.exit(main())
