"""SARIF 2.1.0 output for qrflow (and a structural schema checker).

SARIF is the interchange format CI surfaces (GitHub code scanning, VS
Code SARIF viewers) ingest; qrflow emits the minimal valid subset: one
run, the tool driver with its rule inventory, one ``result`` per finding
and per suppressed finding (the latter carrying an ``inSource``
suppression so viewers render them as waived, not hidden).

``check_sarif`` is a small structural validator for exactly the subset
this module emits — the required-property/type skeleton of the SARIF
2.1.0 spec (§3.13-3.27: version, runs[].tool.driver.name,
results[].message.text, rule ids, physical locations with 1-based
regions).  The test suite runs every emitted document through it, so the
output cannot drift from the spec subset silently; it deliberately has
no dependency on a JSON-Schema library (the image may not ship one).
"""

from __future__ import annotations

from typing import Any

from ..engine import Finding, Rule

SARIF_VERSION = "2.1.0"
SARIF_SCHEMA_URI = "https://json.schemastore.org/sarif-2.1.0.json"


def to_sarif(findings: list[Finding], suppressed: list[Finding],
             rules: list[Rule], tool_name: str = "qrflow") -> dict[str, Any]:
    rule_ids = sorted({f.rule for f in [*findings, *suppressed]}
                      | {r.id for r in rules if r.id})

    def result(f: Finding, waived: bool) -> dict[str, Any]:
        out: dict[str, Any] = {
            "ruleId": f.rule,
            "level": "error" if f.severity == "error" else "warning",
            "message": {"text": f.message},
            "locations": [{
                "physicalLocation": {
                    "artifactLocation": {"uri": f.path.replace("\\", "/")},
                    "region": {"startLine": max(1, f.line),
                               "startColumn": max(1, f.col)},
                },
            }],
        }
        if waived:
            out["suppressions"] = [{"kind": "inSource"}]
        return out

    descriptions = {r.id: r.description for r in rules if r.id}
    return {
        "$schema": SARIF_SCHEMA_URI,
        "version": SARIF_VERSION,
        "runs": [{
            "tool": {"driver": {
                "name": tool_name,
                "informationUri": "https://example.invalid/qrflow",
                "rules": [
                    {"id": rid,
                     "shortDescription": {"text": descriptions.get(rid, rid)}}
                    for rid in rule_ids
                ],
            }},
            "results": [
                *[result(f, waived=False) for f in findings],
                *[result(f, waived=True) for f in suppressed],
            ],
        }],
    }


def check_sarif(doc: Any) -> list[str]:
    """Structural errors for the SARIF subset ``to_sarif`` emits; empty
    list = valid."""
    errors: list[str] = []

    def need(obj, key, typ, where):
        if not isinstance(obj, dict) or key not in obj:
            errors.append(f"{where}: missing required property {key!r}")
            return None
        if not isinstance(obj[key], typ):
            errors.append(f"{where}.{key}: expected {typ.__name__}, "
                          f"got {type(obj[key]).__name__}")
            return None
        return obj[key]

    if need(doc, "version", str, "$") != SARIF_VERSION:
        errors.append(f"$.version: must be {SARIF_VERSION!r}")
    runs = need(doc, "runs", list, "$")
    for i, run in enumerate(runs or []):
        tool = need(run, "tool", dict, f"$.runs[{i}]")
        driver = need(tool or {}, "driver", dict, f"$.runs[{i}].tool")
        need(driver or {}, "name", str, f"$.runs[{i}].tool.driver")
        for j, rule in enumerate((driver or {}).get("rules", [])):
            need(rule, "id", str, f"$.runs[{i}]...rules[{j}]")
        results = need(run, "results", list, f"$.runs[{i}]")
        for j, res in enumerate(results or []):
            where = f"$.runs[{i}].results[{j}]"
            need(res, "ruleId", str, where)
            if res.get("level") not in ("error", "warning", "note", "none"):
                errors.append(f"{where}.level: invalid {res.get('level')!r}")
            msg = need(res, "message", dict, where)
            need(msg or {}, "text", str, f"{where}.message")
            for k, loc in enumerate(res.get("locations", [])):
                lwhere = f"{where}.locations[{k}]"
                phys = need(loc, "physicalLocation", dict, lwhere)
                art = need(phys or {}, "artifactLocation", dict,
                           f"{lwhere}.physicalLocation")
                need(art or {}, "uri", str,
                     f"{lwhere}.physicalLocation.artifactLocation")
                region = (phys or {}).get("region", {})
                for field in ("startLine", "startColumn"):
                    val = region.get(field)
                    if val is not None and (not isinstance(val, int) or val < 1):
                        errors.append(
                            f"{lwhere}...region.{field}: must be a 1-based int")
            for k, sup in enumerate(res.get("suppressions", [])):
                if sup.get("kind") not in ("inSource", "external"):
                    errors.append(f"{where}.suppressions[{k}].kind: invalid")
    return errors
