"""Forward interprocedural taint analysis over a small secrecy lattice.

Lattice (join = max)::

    PUBLIC(0) < ZEROIZED(1) < SECRET_DERIVED(2) < SECRET(3)

* SECRET — raw key material: decapsulated shared secrets, KEM/signature
  secret keys, passwords.
* SECRET_DERIVED — deterministic key-grade derivations (HKDF outputs,
  vault entries): still key material, but one derivation away.
* ZEROIZED — a formerly secret location after an explicit wipe; kept
  distinct from PUBLIC so a wipe is visible in provenance.
* PUBLIC — everything else, including one-way hashes, ciphertexts,
  signatures, and verification results (the crypto-op MODELS below pin
  these down so a signature over a transcript never drags its signing
  key's taint onto the wire).

The analysis is flow-sensitive per function (one forward pass in
statement order), context-insensitive across functions: every call site
joins its argument taints into the callee's parameter vector, callee
return taints come from a per-function SUMMARY, and a worklist iterates
to fixpoint (finite lattice + monotone joins = termination).  The
summary cache — (function, parameter-taint vector) -> summary — skips
re-analysis of anything whose inputs did not change, which is what keeps
the whole-tree CI run cheap.

Tuples are modelled element-wise where it matters: ``generate_keypair``
returns ``(PUBLIC, SECRET)``, so ``pk, sk = kem.generate_keypair()``
taints only ``sk``, and ``self._sig_keypair[0]`` (the public half of a
secret-named pair) stays sendable.

Sinks (reported by packs.py with rule ids):

* logging calls (including the audit log), exception messages,
  ``repr()``/``str()`` and f-string interpolation — exfiltration sinks
  for any taint >= SECRET_DERIVED;
* network sends (``send_message``/``sendall``/``sendto``) — key material
  must never leave before AEAD;
* ``==``/``!=`` on tainted operands in BRANCH POSITION (an if/while/
  ternary test) — a variable-time comparison decision; use
  ``hmac.compare_digest``.  Expression-position comparisons are
  vectorized masking in this codebase (FO re-encryption checks,
  decompose wraps) and stay data-flow on device;
* secret-dependent ``if``/``while`` conditions (ordered comparisons or
  arithmetic on SECRET values) and secret-indexed subscripts — classic
  branch/cache timing channels.  Truthiness (``if secret:``), ``is
  None`` checks and membership tests deliberately do NOT fire: they
  reveal presence, not content.
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Callable

from ..engine import last_attr
from ..rules_secret import _is_logging_call, is_secret_name
from .callgraph import CallGraph, FunctionInfo

PUBLIC, ZEROIZED, DERIVED, SECRET = 0, 1, 2, 3
LEVEL_NAMES = {PUBLIC: "PUBLIC", ZEROIZED: "ZEROIZED",
               DERIVED: "SECRET_DERIVED", SECRET: "SECRET"}


class Taint:
    """A lattice value, optionally structured element-wise for tuples,
    carrying a human-readable provenance (``why``) for findings.  Equality
    ignores provenance so the fixpoint converges on lattice values only."""

    __slots__ = ("level", "elements", "why")

    def __init__(self, level: int, elements: tuple["Taint", ...] | None = None,
                 why: str = ""):
        self.level = level
        self.elements = elements
        self.why = why

    def __eq__(self, other: object) -> bool:
        return (isinstance(other, Taint) and self.level == other.level
                and self.elements == other.elements)

    def __hash__(self) -> int:
        return hash((self.level, self.elements))

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        inner = f", {self.elements!r}" if self.elements else ""
        return f"Taint({LEVEL_NAMES[self.level]}{inner})"


T_PUBLIC = Taint(PUBLIC)

#: element structure deeper than this collapses to a scalar: self-referential
#: flows (``state = (state, x)`` through a fixpoint) would otherwise nest
#: tuples without bound
MAX_TUPLE_DEPTH = 3


def _clip(t: Taint, depth: int = MAX_TUPLE_DEPTH) -> Taint:
    if t.elements is None:
        return t
    if depth <= 0:
        return Taint(t.level, None, t.why)
    clipped = tuple(_clip(e, depth - 1) for e in t.elements)
    if clipped == t.elements:
        return t
    return Taint(t.level, clipped, t.why)


def join(a: Taint, b: Taint) -> Taint:
    if a is b:
        return a
    if a.elements is not None and b.elements is not None:
        if len(a.elements) == len(b.elements):
            elems = tuple(join(x, y) for x, y in zip(a.elements, b.elements))
            return _clip(Taint(max(a.level, b.level), elems, a.why or b.why))
        return Taint(max(a.level, b.level), None, a.why or b.why)
    if a.elements is not None and b.level <= a.level:
        return _clip(a)
    if b.elements is not None and a.level <= b.level:
        return _clip(b)
    return a if a.level >= b.level else Taint(b.level, None, b.why)


#: name suffixes that denote METADATA about a secret, not the secret itself
#: (lengths, shapes, counts, offsets): ``secret_key_len`` is a public size
_METADATA_SUFFIX = ("_len", "_lens", "_length", "_size", "_count", "_num",
                    "_dim", "_ndim", "_off", "_offset", "_idx", "_index",
                    "_shape", "_algo", "_name")

#: attribute reads that yield public metadata of a (possibly secret) array
METADATA_ATTRS = {"shape", "ndim", "dtype", "size", "nbytes", "itemsize",
                  "name"}


def name_taint(name: str | None) -> Taint:
    """Identifier-based seed: secret-named values are SECRET; ``*keypair*``
    names are (public, secret) pairs; metadata-suffixed names (lengths,
    shapes, offsets) are public no matter what they measure."""
    if not name:
        return T_PUBLIC
    low = name.lower()
    if low.endswith(_METADATA_SUFFIX):
        return T_PUBLIC
    if is_secret_name(name):
        if "keypair" in low:
            return Taint(SECRET, (T_PUBLIC, Taint(SECRET, why=f"secret half of {name!r}")),
                         why=f"keypair {name!r}")
        return Taint(SECRET, why=f"secret-named {name!r}")
    return T_PUBLIC


def _pair(why: str) -> Taint:
    return Taint(SECRET, (T_PUBLIC, Taint(SECRET, why=why)), why=why)


#: crypto-op models by callee name: fixed output taints that override
#: propagation (signatures/ciphertexts are public BY CONSTRUCTION even
#: though a secret key went in; decapsulation yields the shared secret).
MODELS: dict[str, Taint] = {
    "generate_keypair": _pair("generate_keypair()"),
    "generate_keypair_batch": _pair("generate_keypair_batch()"),
    "_kem_keygen": _pair("_kem_keygen()"),
    "encapsulate": _pair("encapsulate()"),           # (ct, shared_secret)
    "encapsulate_batch": _pair("encapsulate_batch()"),
    "_kem_encaps": _pair("_kem_encaps()"),
    "decapsulate": Taint(SECRET, why="decapsulate()"),
    "decapsulate_batch": Taint(SECRET, why="decapsulate_batch()"),
    "_kem_decaps": Taint(SECRET, why="_kem_decaps()"),
    "keygen_sign": Taint(SECRET, (T_PUBLIC, Taint(SECRET, why="fused keygen_sign()"),
                                  T_PUBLIC), why="fused keygen_sign()"),
    "encaps_verify_sign": Taint(SECRET, (T_PUBLIC, T_PUBLIC,
                                         Taint(SECRET, why="fused encaps_verify_sign()"),
                                         T_PUBLIC), why="fused encaps_verify_sign()"),
    "decaps_verify_sign": Taint(SECRET, (T_PUBLIC,
                                         Taint(SECRET, why="fused decaps_verify_sign()"),
                                         T_PUBLIC), why="fused decaps_verify_sign()"),
    "sign": T_PUBLIC, "sign_batch": T_PUBLIC, "_sign": T_PUBLIC,
    "verify": T_PUBLIC, "verify_batch": T_PUBLIC, "_verify": T_PUBLIC,
    "encrypt": T_PUBLIC, "decrypt": T_PUBLIC,
    # deterministic-nonce AEAD primitives (provider/base.py): ciphertext
    # out of seal() and plaintext out of open_() are public by the same
    # construction encrypt()/decrypt() are — the key operand never taints
    # the result
    "seal": T_PUBLIC, "open_": T_PUBLIC,
    "seal_batch": T_PUBLIC, "open_batch": T_PUBLIC,
    # session-resumption tickets (app/resumption.py): the STEK-sealed blob
    # is public BY CONSTRUCTION (like a signature/ciphertext — it reveals
    # nothing without the STEK); opening one yields (public metadata,
    # SECRET resumption secret) as a tuple so metadata checks never branch
    # on secret-tainted values; the derivation chain mirrors the KEM one
    # (master secret SECRET, per-resume message key DERIVED)
    "seal_ticket": T_PUBLIC,
    "open_ticket": Taint(SECRET, (T_PUBLIC, Taint(SECRET, why="open_ticket() resumption secret")),
                         why="open_ticket()"),
    "derive_resumption_secret": Taint(SECRET, why="derive_resumption_secret()"),
    "ratchet_resumption_secret": Taint(SECRET, why="ratchet_resumption_secret()"),
    "derive_resumed_key": Taint(DERIVED, why="derive_resumed_key()"),
    "derive_message_key": Taint(DERIVED, why="derive_message_key()"),
    "_hkdf_sha256": Taint(DERIVED, why="_hkdf_sha256()"),
    "hkdf": Taint(DERIVED, why="hkdf()"),
    "hkdf_sha256": Taint(DERIVED, why="hkdf_sha256()"),
    "derive_key": Taint(DERIVED, why="derive_key()"),
    "retrieve": Taint(DERIVED, why="vault retrieve()"),
    "compare_digest": T_PUBLIC,
}

#: calls whose result no longer reveals the input (sizes, hashes, types)
SANITIZERS = {
    "len", "type", "bool", "id", "hash", "sha256", "sha384", "sha512",
    "sha3_256", "sha3_512", "blake2b", "blake2s", "md5",
    "hexdigest", "digest",
}

#: call names that wipe their argument / receiver in place
WIPERS = {"wipe", "_wipe", "zeroize", "_zeroize", "_wipe_secret", "wipe_secret"}

#: values that leave the process on a socket.  ``_respond`` is the HTTP
#: telemetry surface's single response-write chokepoint (obs/http.py):
#: whatever reaches it is served to whoever scrapes the endpoint, so the
#: same pre-AEAD rule applies — response bodies may be built only from
#: registry snapshots / SLO reports / span dumps (public by
#: construction), never key material.
#: ``_send_frame_bin`` is the negotiated binary wire's single encode
#: chokepoint (net/p2p_node.py): raw bytes values in the message dict hit
#: the socket UNENCODED — the pre-AEAD rule applies to it exactly as to
#: send_message, and a secret smuggled into a binary field would leave the
#: process verbatim.
NETWORK_SINKS = {"send_message", "sendall", "sendto", "_respond",
                 "_send_frame_bin"}

#: observability sinks (obs/): span attributes, metric labels, and
#: flight-recorder payloads are exported in cleartext diagnostics (trace
#: files, Prometheus scrapes, flight bundles) — key material must never
#: reach them.  ``wire_context``/``adopt_wire_context`` are the
#: cross-peer propagation surface (obs/trace.py): whatever reaches them
#: RIDES THE NETWORK in the ``_trace`` frame field, so the same rule
#: guarantees only correlation ids ever do.  Unconditional method names
#: first; the generic names below count only on an obs-looking receiver
#: (``TRACER.span``, ``obs_trace.span``, ``flight.record``,
#: ``RECORDER.trigger``) so an unrelated ``foo.record()`` stays quiet.
TRACE_SINKS = {"set_attr", "add_event", "labels",
               "wire_context", "adopt_wire_context"}
TRACE_SINKS_BY_RECEIVER = {"span", "record", "record_event", "trigger"}
TRACE_RECEIVER_HINTS = ("trace", "tracer", "flight", "recorder", "metric")

#: vectorized masked-select primitives: an ``==``/``<`` producing a MASK for
#: these is data-flow selection (constant-time by construction), not a
#: variable-time comparison
MASK_FNS = {"where", "select", "select_n", "cond", "switch",
            "dynamic_update_slice", "dynamic_slice"}


@dataclasses.dataclass
class Summary:
    ret: Taint = dataclasses.field(default_factory=lambda: T_PUBLIC)


@dataclasses.dataclass
class SinkHit:
    rule: str
    fn: FunctionInfo
    node: ast.AST
    message: str


class TaintPass:
    """One flow-sensitive forward pass over a single function body."""

    def __init__(self, fn: FunctionInfo, cg: CallGraph,
                 summaries: dict[str, Summary],
                 param_taint: dict[str, list[Taint]],
                 report: Callable[[SinkHit], None] | None = None):
        self.fn = fn
        self.cg = cg
        self.summaries = summaries
        self.param_taint = param_taint
        self.report = report
        self.env: dict[str, Taint] = {}
        self.ret = T_PUBLIC
        #: >0 while evaluating args of a masked-select primitive (MASK_FNS)
        self._mask_depth = 0
        #: >0 while evaluating an if/while/ternary TEST — the only position
        #: where ==/!= on key material is a variable-time decision; in
        #: expression position it is vectorized masking (FO re-encryption
        #: checks, decompose wraps) that stays data-flow on device
        self._branch_depth = 0
        #: callee fid -> joined positional taints observed at call sites
        self.callee_updates: dict[str, dict[int, Taint]] = {}
        params = fn.params
        incoming = param_taint.get(fn.fid, [])
        for i, p in enumerate(params):
            seed = name_taint(p)
            if i < len(incoming):
                seed = join(seed, incoming[i])
            if seed.level > PUBLIC or seed.elements:
                self.env[p] = seed

    # -- driving --------------------------------------------------------------

    def run(self) -> None:
        for stmt in getattr(self.fn.node, "body", []):
            self.exec_stmt(stmt)

    def _hit(self, rule: str, node: ast.AST, message: str) -> None:
        if self.report is not None:
            self.report(SinkHit(rule, self.fn, node, message))

    # -- statements -----------------------------------------------------------

    def exec_stmt(self, stmt: ast.stmt) -> None:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
            return  # analyzed as their own functions
        if isinstance(stmt, ast.Assign):
            val = self.eval(stmt.value)
            for t in stmt.targets:
                self.assign(t, val, stmt.value)
        elif isinstance(stmt, ast.AnnAssign) and stmt.value is not None:
            self.assign(stmt.target, self.eval(stmt.value), stmt.value)
        elif isinstance(stmt, ast.AugAssign):
            val = join(self.eval(stmt.target), self.eval(stmt.value))
            self.assign(stmt.target, val, stmt.value)
        elif isinstance(stmt, ast.Return):
            if stmt.value is not None:
                self.ret = join(self.ret, self.eval(stmt.value))
        elif isinstance(stmt, ast.Expr):
            self.eval(stmt.value)
        elif isinstance(stmt, (ast.If, ast.While)):
            self.check_condition(stmt.test)
            self._branch_depth += 1
            try:
                self.eval(stmt.test)
            finally:
                self._branch_depth -= 1
            for s in [*stmt.body, *stmt.orelse]:
                self.exec_stmt(s)
        elif isinstance(stmt, (ast.For, ast.AsyncFor)):
            it = self.eval(stmt.iter)
            target_taint = Taint(it.level, None, it.why)
            if (isinstance(stmt.target, ast.Tuple)
                    and isinstance(stmt.iter, (ast.Tuple, ast.List))
                    and stmt.iter.elts
                    and all(isinstance(e, ast.Tuple)
                            and len(e.elts) == len(stmt.target.elts)
                            for e in stmt.iter.elts)):
                # for (name, value) in (("sk_seed", sk_seed), ...): join the
                # iterable COLUMN-wise so the label stays public
                cols = [T_PUBLIC] * len(stmt.target.elts)
                for row in stmt.iter.elts:
                    for i, cell in enumerate(row.elts):
                        cols[i] = join(cols[i], self.eval(cell))
                target_taint = Taint(max(c.level for c in cols), tuple(cols),
                                     it.why)
            self.assign(stmt.target, target_taint, stmt.iter)
            for s in [*stmt.body, *stmt.orelse]:
                self.exec_stmt(s)
        elif isinstance(stmt, (ast.With, ast.AsyncWith)):
            for item in stmt.items:
                val = self.eval(item.context_expr)
                if item.optional_vars is not None:
                    self.assign(item.optional_vars, val, item.context_expr)
            for s in stmt.body:
                self.exec_stmt(s)
        elif isinstance(stmt, ast.Try):
            for s in stmt.body:
                self.exec_stmt(s)
            for handler in stmt.handlers:
                for s in handler.body:
                    self.exec_stmt(s)
            for s in [*stmt.orelse, *stmt.finalbody]:
                self.exec_stmt(s)
        elif isinstance(stmt, ast.Raise):
            if isinstance(stmt.exc, ast.Call):
                for arg in [*stmt.exc.args,
                            *[kw.value for kw in stmt.exc.keywords]]:
                    t = self.eval(arg)
                    if t.level >= DERIVED:
                        self._hit(
                            "flow-secret-in-exception", arg,
                            f"{LEVEL_NAMES[t.level]} value"
                            f"{_why(t)} embedded in an exception message "
                            "(exceptions end up in logs and tracebacks)",
                        )
            elif stmt.exc is not None:
                self.eval(stmt.exc)
        elif isinstance(stmt, ast.Delete):
            for t in stmt.targets:
                path = _target_path(t)
                if path:
                    self.env[path] = Taint(ZEROIZED, why="deleted")
        # Assert/Pass/Import/Global/Nonlocal/Break/Continue: no taint effect

    def assign(self, target: ast.AST, val: Taint, value_node: ast.AST) -> None:
        if isinstance(target, (ast.Tuple, ast.List)):
            elems = val.elements
            for i, t in enumerate(target.elts):
                if elems is not None and i < len(elems):
                    self.assign(t, elems[i], value_node)
                else:
                    self.assign(t, Taint(val.level, None, val.why), value_node)
            return
        path = _target_path(target)
        if path is None:
            if isinstance(target, ast.Subscript):
                base = _target_path(target.value)
                if base is not None:   # d[k] = v joins into the container
                    self.env[base] = join(self.env.get(base, T_PUBLIC),
                                          Taint(val.level, None, val.why))
            return
        prev = self.env.get(path)
        if _is_empty_const(value_node) and prev is not None and prev.level >= DERIVED:
            self.env[path] = Taint(ZEROIZED, why=f"{path} cleared")
        else:
            self.env[path] = val

    # -- sink checks ----------------------------------------------------------

    def check_condition(self, test: ast.AST) -> None:
        """Secret-dependent control flow: ordered comparisons or arithmetic
        on SECRET inside an if/while test.  (Eq/NotEq anywhere is already
        the compare sink; truthiness / is-None / membership stay quiet.)"""
        for node in ast.walk(test):
            if isinstance(node, ast.Compare):
                ops = [type(op) for op in node.ops]
                if any(op in (ast.Lt, ast.LtE, ast.Gt, ast.GtE) for op in ops):
                    for side in (node.left, *node.comparators):
                        t = self.eval(side)
                        if t.level >= SECRET:
                            self._hit(
                                "flow-secret-branch", node,
                                f"branch depends on an ordered comparison of a "
                                f"SECRET value{_why(t)} — a timing side channel",
                            )
                            break
            elif isinstance(node, ast.BinOp):
                t = join(self.eval(node.left), self.eval(node.right))
                if t.level >= SECRET:
                    self._hit(
                        "flow-secret-branch", node,
                        f"branch depends on arithmetic over a SECRET value"
                        f"{_why(t)} — a timing side channel",
                    )

    def _check_compare(self, node: ast.Compare) -> None:
        if self._branch_depth <= 0:
            return  # expression position: vectorized masking, not a branch
        if self._mask_depth > 0:
            return  # masked selection: constant-time by construction
        if not any(isinstance(op, (ast.Eq, ast.NotEq)) for op in node.ops):
            return
        sides = [node.left, *node.comparators]
        if any(isinstance(s, ast.Constant) and s.value is None for s in sides):
            return
        # ``arange(n) == x`` builds a one-hot/iota mask, not a comparison
        for s in sides:
            if isinstance(s, ast.Call) and (last_attr(s.func) or "") in (
                    "arange", "iota"):
                return
        for side in sides:
            t = self.eval(side)
            if t.level >= DERIVED:
                self._hit(
                    "flow-secret-compare", node,
                    f"{LEVEL_NAMES[t.level]} value{_why(t)} compared with "
                    "==/!= — a variable-time comparison; use "
                    "hmac.compare_digest",
                )
                return

    # -- expressions ----------------------------------------------------------

    def eval(self, node: ast.AST) -> Taint:
        if isinstance(node, ast.Constant):
            return T_PUBLIC
        if isinstance(node, ast.Name):
            return self.env.get(node.id, name_taint(node.id))
        if isinstance(node, ast.Attribute):
            path = _target_path(node)
            if path is not None and path in self.env:
                return self.env[path]
            if node.attr in METADATA_ATTRS:
                return T_PUBLIC   # sk.shape / arr.dtype are public metadata
            base = self.eval(node.value) if not (
                isinstance(node.value, ast.Name) and node.value.id == "self"
            ) else T_PUBLIC
            return join(base, name_taint(node.attr))
        if isinstance(node, ast.Await):
            return self.eval(node.value)
        if isinstance(node, ast.Call):
            return self.eval_call(node)
        if isinstance(node, ast.Compare):
            self._check_compare(node)
            for side in (node.left, *node.comparators):
                self.eval(side)
            return T_PUBLIC
        if isinstance(node, ast.BoolOp):
            out = T_PUBLIC
            for v in node.values:
                out = join(out, self.eval(v))
            return out
        if isinstance(node, ast.BinOp):
            return join(self.eval(node.left), self.eval(node.right))
        if isinstance(node, ast.UnaryOp):
            return self.eval(node.operand)
        if isinstance(node, ast.IfExp):
            self._branch_depth += 1
            try:
                self.eval(node.test)
            finally:
                self._branch_depth -= 1
            return join(self.eval(node.body), self.eval(node.orelse))
        if isinstance(node, (ast.Tuple, ast.List)):
            elems = tuple(self.eval(e) for e in node.elts)
            level = max((e.level for e in elems), default=PUBLIC)
            why = next((e.why for e in elems if e.level == level and e.why), "")
            return Taint(level, elems if isinstance(node, ast.Tuple) else None, why)
        if isinstance(node, (ast.Set, ast.Dict)):
            out = T_PUBLIC
            vals = node.values if isinstance(node, ast.Dict) else node.elts
            for v in vals:
                if v is not None:
                    out = join(out, self.eval(v))
            return Taint(out.level, None, out.why)
        if isinstance(node, ast.Subscript):
            return self.eval_subscript(node)
        if isinstance(node, ast.JoinedStr):
            out = T_PUBLIC
            for part in node.values:
                if isinstance(part, ast.FormattedValue):
                    t = self.eval(part.value)
                    if t.level >= DERIVED:
                        self._hit(
                            "flow-secret-format", part,
                            f"f-string interpolates a {LEVEL_NAMES[t.level]} "
                            f"value{_why(t)} — the rendered string carries key "
                            "material wherever it goes",
                        )
                    out = join(out, t)
            return Taint(out.level, None, out.why)
        if isinstance(node, (ast.ListComp, ast.SetComp, ast.GeneratorExp, ast.DictComp)):
            out = T_PUBLIC
            for gen in node.generators:
                it = self.eval(gen.iter)
                self.assign(gen.target, Taint(it.level, None, it.why), gen.iter)
            for part in ([node.key, node.value] if isinstance(node, ast.DictComp)
                         else [node.elt]):
                out = join(out, self.eval(part))
            return Taint(out.level, None, out.why)
        if isinstance(node, ast.Starred):
            return self.eval(node.value)
        if isinstance(node, ast.Lambda):
            return T_PUBLIC
        return T_PUBLIC

    def eval_subscript(self, node: ast.Subscript) -> Taint:
        base = self.eval(node.value)
        idx = node.slice
        idx_taint = self.eval(idx) if not isinstance(idx, ast.Slice) else T_PUBLIC
        if idx_taint.level >= SECRET:
            self._hit(
                "flow-secret-branch", node,
                f"subscript indexed by a SECRET value{_why(idx_taint)} — a "
                "cache-timing side channel (table lookups must not be "
                "secret-addressed)",
            )
        if base.elements is not None and isinstance(idx, ast.Constant) and isinstance(
                idx.value, int):
            i = idx.value
            if -len(base.elements) <= i < len(base.elements):
                return base.elements[i]
        if (base.level >= DERIVED and isinstance(idx, ast.Constant)
                and isinstance(idx.value, str)):
            from ..rules_secret import NONSECRET_NAME_RE

            if NONSECRET_NAME_RE.search(idx.value):
                return T_PUBLIC  # stored["public"] — the public half
        return Taint(base.level, None, base.why)

    def eval_call(self, call: ast.Call) -> Taint:
        leaf = last_attr(call.func) or ""
        arg_nodes = [*call.args, *[kw.value for kw in call.keywords]]
        if leaf in MASK_FNS:
            self._mask_depth += 1
            try:
                arg_taints = [self.eval(a) for a in arg_nodes]
            finally:
                self._mask_depth -= 1
        else:
            arg_taints = [self.eval(a) for a in arg_nodes]

        # sink: logging (incl. the audit log), repr()/str()
        if _is_logging_call(call):
            for a, t in zip(arg_nodes, arg_taints):
                if t.level >= DERIVED:
                    self._hit(
                        "flow-secret-in-log", a,
                        f"{LEVEL_NAMES[t.level]} value{_why(t)} flows into "
                        f"logging sink {leaf!r}",
                    )
        if isinstance(call.func, ast.Name) and call.func.id in ("repr", "str"):
            for t in arg_taints:
                if t.level >= DERIVED:
                    self._hit(
                        "flow-secret-format", call,
                        f"{call.func.id}() of a {LEVEL_NAMES[t.level]} value"
                        f"{_why(t)} renders key material",
                    )
        # sink: network send before AEAD
        if leaf in NETWORK_SINKS:
            for a, t in zip(arg_nodes, arg_taints):
                if t.level >= DERIVED:
                    self._hit(
                        "flow-secret-to-network", a,
                        f"{LEVEL_NAMES[t.level]} value{_why(t)} passed to "
                        f"network sink {leaf!r} without AEAD",
                    )
        # sink: observability (span attrs / metric labels / flight payloads)
        if self._is_trace_sink(call, leaf):
            for a, t in zip(arg_nodes, arg_taints):
                if t.level >= DERIVED:
                    self._hit(
                        "flow-secret-in-trace", a,
                        f"{LEVEL_NAMES[t.level]} value{_why(t)} passed to "
                        f"observability sink {leaf!r} — span attributes, "
                        "metric labels, and flight-recorder payloads are "
                        "exported in cleartext diagnostics",
                    )
        # wipes
        if leaf in WIPERS:
            for a in call.args:
                path = _target_path(a)
                if path is not None:
                    self.env[path] = Taint(ZEROIZED, why=f"wiped by {leaf}()")
            recv = call.func.value if isinstance(call.func, ast.Attribute) else None
            path = _target_path(recv) if recv is not None else None
            if path is not None:
                self.env[path] = Taint(ZEROIZED, why=f"wiped by {leaf}()")
            return T_PUBLIC

        # interprocedural propagation into resolved callees
        sites = self.cg.edges_at.get(id(call), [])
        for site in sites:
            self._propagate_args(site, call, arg_taints)

        # result taint: model > sanitizer > summaries > propagate
        if leaf == "retrieve":
            # vault lookups: only secret-named entries are key material
            # (identity records, peer aliases, settings stay public)
            arg0 = call.args[0] if call.args else None
            entry = None
            if isinstance(arg0, ast.Constant) and isinstance(arg0.value, str):
                entry = arg0.value
            elif isinstance(arg0, ast.Name):
                entry = self._module_const(arg0.id)
            if (entry is not None and not is_secret_name(entry)
                    and "key" not in entry.lower()):
                return T_PUBLIC
            return MODELS[leaf]
        if leaf in MODELS:
            return MODELS[leaf]
        if leaf in SANITIZERS:
            return T_PUBLIC
        rets = [self.summaries[s.callee.fid].ret for s in sites
                if s.kind in ("call", "await") and s.callee.fid in self.summaries]
        if rets:
            out = rets[0]
            for r in rets[1:]:
                out = join(out, r)
            return out
        out = T_PUBLIC
        for t in arg_taints:
            out = join(out, Taint(t.level, None, t.why))
        if isinstance(call.func, ast.Attribute):
            recv_t = self.eval(call.func.value)
            out = join(out, Taint(recv_t.level, None, recv_t.why))
        return out

    @staticmethod
    def _is_trace_sink(call: ast.Call, leaf: str) -> bool:
        """Observability-sink classification (see TRACE_SINKS above)."""
        if leaf in TRACE_SINKS:
            return True
        if leaf not in TRACE_SINKS_BY_RECEIVER:
            return False
        if isinstance(call.func, ast.Name):
            # `from obs.trace import span` usage: the bare name IS the sink
            return call.func.id == "span"
        if isinstance(call.func, ast.Attribute):
            from ..engine import dotted_name

            recv = (dotted_name(call.func.value)
                    or last_attr(call.func.value) or "")
            return any(h in recv.lower() for h in TRACE_RECEIVER_HINTS)
        return False

    def _module_const(self, name: str) -> str | None:
        """Value of a module-level ``NAME = "literal"`` in this file."""
        cache = getattr(self.fn.ctx, "_qrflow_consts", None)
        if cache is None:
            cache = {}
            for node in self.fn.ctx.tree.body:
                if (isinstance(node, ast.Assign) and len(node.targets) == 1
                        and isinstance(node.targets[0], ast.Name)
                        and isinstance(node.value, ast.Constant)
                        and isinstance(node.value.value, str)):
                    cache[node.targets[0].id] = node.value.value
            self.fn.ctx._qrflow_consts = cache  # type: ignore[attr-defined]
        return cache.get(name)

    def _propagate_args(self, site, call: ast.Call, arg_taints: list[Taint]) -> None:
        callee = site.callee
        params = callee.params
        offset = 0
        if params and params[0] == "self" and (
                isinstance(call.func, ast.Attribute) or site.kind == "partial"):
            offset = 1
        updates = self.callee_updates.setdefault(callee.fid, {})
        pos_taints = arg_taints[: len(call.args)]
        kw_taints = arg_taints[len(call.args):]
        if site.kind == "partial":
            pos_taints = pos_taints[1:]   # args[0] is the callable itself
        for i, t in enumerate(pos_taints):
            if t.level > PUBLIC or t.elements:
                idx = i + offset
                if idx < len(params):
                    updates[idx] = join(updates.get(idx, T_PUBLIC), t)
        for kw, t in zip(call.keywords, kw_taints):
            if kw.arg and kw.arg in params and (t.level > PUBLIC or t.elements):
                idx = params.index(kw.arg)
                updates[idx] = join(updates.get(idx, T_PUBLIC), t)


def _target_path(node: ast.AST) -> str | None:
    """Env key for a Name or dotted self-attribute chain."""
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        base = _target_path(node.value)
        if base is not None:
            return f"{base}.{node.attr}"
    return None


def _is_empty_const(node: ast.AST) -> bool:
    return isinstance(node, ast.Constant) and not node.value


def _why(t: Taint) -> str:
    return f" (from {t.why})" if t.why else ""


class TaintEngine:
    """Worklist fixpoint over per-function summaries with a summary cache."""

    MAX_VISITS = 24   # safety valve; the lattice bounds real iteration counts

    def __init__(self, cg: CallGraph):
        self.cg = cg
        self.summaries: dict[str, Summary] = {
            fid: Summary() for fid in cg.functions}
        self.param_taint: dict[str, list[Taint]] = {}
        #: fid -> {param vector -> (return taint, callee arg-taint updates)}:
        #: the summary cache — a pass whose inputs (param taints AND callee
        #: summaries) are unchanged is a pure replay.  Entries are dropped
        #: for every caller whenever a callee's summary rises.
        self._cache: dict[str, dict[tuple[Taint, ...],
                                    tuple[Taint, dict[str, dict[int, Taint]]]]] = {}
        self.cache_hits = 0

    def _params_key(self, fid: str) -> tuple[Taint, ...]:
        return tuple(self.param_taint.get(fid, []))

    def solve(self) -> None:
        order = sorted(self.cg.functions)
        visits: dict[str, int] = {}
        work = list(order)
        queued = set(order)
        while work:
            fid = work.pop(0)
            queued.discard(fid)
            if visits.get(fid, 0) >= self.MAX_VISITS:
                continue
            visits[fid] = visits.get(fid, 0) + 1
            fn = self.cg.functions[fid]
            key = self._params_key(fid)
            cached = self._cache.get(fid, {}).get(key)
            if cached is not None:
                # summary cache: same function + same parameter taints (and
                # no callee-summary change since, which invalidates below)
                # means the pass is a pure replay — reuse, skip the walk
                self.cache_hits += 1
                ret, callee_updates = cached
            else:
                tp = TaintPass(fn, self.cg, self.summaries, self.param_taint)
                tp.run()
                ret, callee_updates = tp.ret, tp.callee_updates
                self._cache.setdefault(fid, {})[key] = (ret, callee_updates)

            def enqueue(f: str) -> None:
                if f not in queued:
                    queued.add(f)
                    work.append(f)

            # push argument taints into callees
            for callee_fid, updates in callee_updates.items():
                callee = self.cg.functions.get(callee_fid)
                if callee is None:
                    continue
                vec = self.param_taint.setdefault(
                    callee_fid, [T_PUBLIC] * len(callee.params))
                changed = False
                for idx, t in updates.items():
                    if idx < len(vec):
                        new = join(vec[idx], t)
                        if new != vec[idx]:
                            vec[idx] = new
                            changed = True
                if changed:
                    enqueue(callee_fid)
            # publish the return summary (monotone: only a JOIN that actually
            # raises the summary re-enqueues callers)
            new_ret = join(self.summaries[fid].ret, ret)
            if new_ret != self.summaries[fid].ret:
                self.summaries[fid].ret = new_ret
                for site in self.cg.edges_by_callee.get(fid, []):
                    # the caller's cached passes saw the OLD summary
                    self._cache.pop(site.caller.fid, None)
                    enqueue(site.caller.fid)

    def report_pass(self, include: Callable[[FunctionInfo], bool],
                    report: Callable[[SinkHit], None]) -> None:
        """Final pass with stable summaries, emitting sink findings."""
        for fid in sorted(self.cg.functions):
            fn = self.cg.functions[fid]
            if not include(fn):
                continue
            TaintPass(fn, self.cg, self.summaries, self.param_taint,
                      report=report).run()
