"""Project-wide call graph for qrflow.

One indexing pass over every parsed file builds function/class/module
tables; a second pass resolves call sites to project functions.  The
resolution ladder, most precise first:

1. lexical names — nested functions (closures), module functions, and
   ``from x import y`` imports of linted modules;
2. ``self.m(...)`` — the enclosing class's MRO (name-based, like the
   provider-contract rule) plus subclass overrides, since a self call can
   dispatch to either;
3. typed receivers — locals/attributes assigned from ``ClassName(...)``
   or from a provider-registry getter (``get_kem``/``get_signature``/
   ``get_fused``/``get_symmetric``), which resolve to every implementation
   class named at a ``register_*`` call site (registry dispatch);
4. fallback — a method name defined by at most ``FALLBACK_MAX`` project
   classes resolves to all of them (sound-ish; wildly common names stay
   unresolved rather than connecting everything to everything).

Besides plain calls the graph records DEFERRED edges with a kind that the
ownership-domain inference (domains.py) seeds from: ``thread``
(``threading.Thread(target=...)``), ``executor`` (``run_in_executor`` /
``.submit``), ``loop_cb`` (``call_soon``/``call_later``/asyncio
``add_done_callback``, plus fleet ``on_event`` handler registrations —
they fire from the control read loops / health tick), ``task``
(``create_task``/``ensure_future``), ``subprocess``
(``create_subprocess_exec`` of a ``python -m <project module>`` worker —
the fleet gateway spawn — resolved to that module's ``main``),
``partial`` (``functools.partial`` — bound arguments feed the taint
pass), ``await`` (async edges), and ``ref`` (a bare function reference
passed as an argument).
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Iterable

from ..engine import FileContext, Project, dotted_name, last_attr

#: a method name defined by more than this many classes is too generic to
#: fallback-resolve (precision over recall)
FALLBACK_MAX = 8

#: attribute calls that MUTATE their receiver's container attribute
#: (``x.attr.add(v)`` counts as a write of ``attr`` for the race pack)
MUTATORS = {
    "add", "append", "extend", "update", "insert", "remove", "discard",
    "pop", "popitem", "clear", "setdefault", "move_to_end", "record",
}

_REGISTRY_GETTERS = {
    "get_kem": "register_kem",
    "get_signature": "register_signature",
    "get_fused": "register_fused",
    "get_symmetric": "_AEADS",
}


@dataclasses.dataclass
class FunctionInfo:
    fid: str
    name: str
    qualname: str
    node: ast.AST
    ctx: FileContext
    path: str
    class_name: str | None
    parent: "FunctionInfo | None"
    is_async: bool
    params: list[str]
    children: dict[str, "FunctionInfo"] = dataclasses.field(default_factory=dict)

    @property
    def is_init(self) -> bool:
        return self.name in ("__init__", "__post_init__")


@dataclasses.dataclass
class ClassInfo:
    name: str
    node: ast.ClassDef
    ctx: FileContext
    path: str
    bases: list[str]
    methods: dict[str, FunctionInfo] = dataclasses.field(default_factory=dict)
    #: attributes assigned to ``self`` anywhere in the class (plus
    #: dataclass-style annotated fields)
    attrs: set[str] = dataclasses.field(default_factory=set)


@dataclasses.dataclass
class ModuleInfo:
    path: str
    ctx: FileContext
    functions: dict[str, FunctionInfo] = dataclasses.field(default_factory=dict)
    classes: dict[str, ClassInfo] = dataclasses.field(default_factory=dict)
    #: local alias -> ("module/path/suffix", imported-name-or-None)
    imports: dict[str, tuple[str, str | None]] = dataclasses.field(default_factory=dict)


@dataclasses.dataclass
class CallSite:
    caller: FunctionInfo
    callee: FunctionInfo
    node: ast.AST
    kind: str  # call | await | partial | thread | executor | loop_cb | task | subprocess | ref
    label: str = ""   # thread name, when known
    bound: int = 0    # positional args bound by a partial


def _base_names(cls: ast.ClassDef) -> list[str]:
    out = []
    for base in cls.bases:
        name = last_attr(base)
        if name:
            out.append(name)
    return out


def _import_suffix(module: str | None, level: int) -> str:
    """Best-effort path suffix for an imported module (relative imports
    drop the dots; absolute imports keep the dotted tail)."""
    return (module or "").replace(".", "/")


class CallGraph:
    """Functions, classes, and resolved call edges of one project run."""

    def __init__(self, project: Project):
        self.project = project
        self.functions: dict[str, FunctionInfo] = {}
        self.modules: dict[str, ModuleInfo] = {}
        self.classes: dict[str, ClassInfo] = {}          # last definition wins
        self.by_method_name: dict[str, list[FunctionInfo]] = {}
        self.subclasses: dict[str, set[str]] = {}
        self.registry_impls: dict[str, set[str]] = {g: set() for g in _REGISTRY_GETTERS}
        #: class name -> attr -> set of class names the attr may hold
        self.class_attr_types: dict[str, dict[str, set[str]]] = {}
        self.edges: list[CallSite] = []
        self.edges_by_caller: dict[str, list[CallSite]] = {}
        self.edges_by_callee: dict[str, list[CallSite]] = {}
        #: id(Call node) -> call sites resolved from that exact node
        self.edges_at: dict[int, list[CallSite]] = {}

        for ctx in project.contexts.values():
            self._index_module(ctx)
        self._index_registry()
        self._index_subclasses()
        self._index_attr_types()
        for mod in self.modules.values():
            for fn in _walk_functions(mod):
                self._build_edges(fn, mod)

    # -- indexing -------------------------------------------------------------

    def _index_module(self, ctx: FileContext) -> None:
        mod = ModuleInfo(ctx.path, ctx)
        self.modules[ctx.path] = mod
        # imports anywhere in the module (function-local deferred imports are
        # idiomatic here — ``from ..provider import health`` inside the warmup
        # closure — and must still resolve for domain propagation)
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.ImportFrom):
                suffix = _import_suffix(node.module, node.level)
                for alias in node.names:
                    mod.imports.setdefault(alias.asname or alias.name,
                                           (suffix, alias.name))
            elif isinstance(node, ast.Import):
                for alias in node.names:
                    mod.imports.setdefault(alias.asname or alias.name,
                                           (alias.name.replace(".", "/"), None))

        def index_fn(node, class_name, parent, prefix):
            qualname = f"{prefix}{node.name}"
            fid = f"{ctx.path}::{qualname}"
            params = [a.arg for a in [*node.args.posonlyargs, *node.args.args]]
            fn = FunctionInfo(
                fid=fid, name=node.name, qualname=qualname, node=node, ctx=ctx,
                path=ctx.path, class_name=class_name, parent=parent,
                is_async=isinstance(node, ast.AsyncFunctionDef), params=params,
            )
            self.functions[fid] = fn
            if parent is not None:
                parent.children[node.name] = fn
            for child in node.body:
                index_stmt(child, class_name, fn, f"{qualname}.<locals>.")
            return fn

        def index_stmt(node, class_name, parent_fn, prefix):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                fn = index_fn(node, class_name, parent_fn, prefix)
                if class_name is not None and parent_fn is None:
                    cls = mod.classes[class_name]
                    cls.methods[node.name] = fn
                    self.by_method_name.setdefault(node.name, []).append(fn)
                elif parent_fn is None:
                    mod.functions[node.name] = fn
            elif isinstance(node, ast.ClassDef) and parent_fn is None:
                cls = ClassInfo(node.name, node, ctx, ctx.path, _base_names(node))
                mod.classes[node.name] = cls
                self.classes[node.name] = cls
                for item in node.body:
                    if isinstance(item, ast.AnnAssign) and isinstance(item.target, ast.Name):
                        cls.attrs.add(item.target.id)   # dataclass-style field
                    index_stmt(item, node.name, None, f"{node.name}.")
                for sub in ast.walk(node):
                    if isinstance(sub, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
                        targets = sub.targets if isinstance(sub, ast.Assign) else [sub.target]
                        for t in targets:
                            if (isinstance(t, ast.Attribute)
                                    and isinstance(t.value, ast.Name)
                                    and t.value.id == "self"):
                                cls.attrs.add(t.attr)
            else:
                for child in ast.iter_child_nodes(node):
                    if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                          ast.ClassDef)):
                        index_stmt(child, class_name, parent_fn, prefix)

        for node in ctx.tree.body:
            index_stmt(node, None, None, "")

    def _index_registry(self) -> None:
        """Classes named at ``register_*`` call sites (and in the AEAD
        table) — what a registry getter's result can be at runtime."""
        inv = {v: k for k, v in _REGISTRY_GETTERS.items()}
        for ctx in self.project.contexts.values():
            for node in ast.walk(ctx.tree):
                if isinstance(node, ast.Call):
                    fname = (dotted_name(node.func) or "").split(".")[-1]
                    getter = inv.get(fname)
                    if getter is None:
                        continue
                    for sub in ast.walk(node):
                        if (isinstance(sub, ast.Call)
                                and isinstance(sub.func, ast.Name)
                                and sub.func.id[:1].isupper()):
                            self.registry_impls[getter].add(sub.func.id)
                elif isinstance(node, (ast.Assign, ast.AnnAssign)):
                    targets = (node.targets if isinstance(node, ast.Assign)
                               else [node.target])
                    names = [getattr(t, "id", None) for t in targets]
                    if "_AEADS" in names and isinstance(node.value, ast.Dict):
                        for v in node.value.values:
                            if isinstance(v, ast.Name):
                                self.registry_impls["get_symmetric"].add(v.id)

    def _index_subclasses(self) -> None:
        for cls in self.classes.values():
            for base in cls.bases:
                self.subclasses.setdefault(base, set()).add(cls.name)

    def _transitive_subclasses(self, name: str) -> set[str]:
        out: set[str] = set()
        stack = [name]
        while stack:
            for sub in self.subclasses.get(stack.pop(), ()):
                if sub not in out:
                    out.add(sub)
                    stack.append(sub)
        return out

    def _index_attr_types(self) -> None:
        """``self.attr = ClassName(...)`` / ``self.attr = get_kem(...)``
        assignments, collected class-wide (flow-insensitive)."""
        for cls in self.classes.values():
            table = self.class_attr_types.setdefault(cls.name, {})
            for node in ast.walk(cls.node):
                if not isinstance(node, ast.Assign):
                    continue
                types = self.value_types(node.value, {})
                if not types:
                    continue
                for t in node.targets:
                    if (isinstance(t, ast.Attribute)
                            and isinstance(t.value, ast.Name) and t.value.id == "self"):
                        table.setdefault(t.attr, set()).update(types)

    # -- type-ish resolution --------------------------------------------------

    def value_types(self, node: ast.AST, local_types: dict[str, set[str]]) -> set[str]:
        """Possible project class names for the value of ``node``."""
        if isinstance(node, ast.Call):
            fname = dotted_name(node.func) or ""
            leaf = fname.split(".")[-1]
            if leaf in _REGISTRY_GETTERS:
                return set(self.registry_impls[leaf])
            if isinstance(node.func, ast.Name) and node.func.id in self.classes:
                return {node.func.id}
            if (isinstance(node.func, ast.Attribute)
                    and node.func.attr in self.classes):
                return {node.func.attr}
            return set()
        if isinstance(node, ast.Name):
            return set(local_types.get(node.id, ()))
        if isinstance(node, ast.Attribute) and isinstance(node.value, ast.Name):
            if node.value.id == "self":
                return set()  # caller consults class_attr_types with context
            return set()
        if isinstance(node, ast.IfExp):
            return (self.value_types(node.body, local_types)
                    | self.value_types(node.orelse, local_types))
        return set()

    def mro_methods(self, cls_name: str) -> dict[str, FunctionInfo]:
        out: dict[str, FunctionInfo] = {}
        seen: set[str] = set()

        def collect(name: str) -> None:
            if name in seen or name not in self.classes:
                return
            seen.add(name)
            cls = self.classes[name]
            for mname, fn in cls.methods.items():
                out.setdefault(mname, fn)
            for base in cls.bases:
                collect(base)

        collect(cls_name)
        return out

    # -- edge construction ----------------------------------------------------

    def _module_function(self, suffix: str, name: str | None) -> FunctionInfo | None:
        for path, mod in self.modules.items():
            norm = path.replace("\\", "/")
            if suffix and (norm.endswith(suffix + ".py")
                           or norm.endswith(suffix + "/__init__.py")):
                if name is None:
                    return None
                return mod.functions.get(name)
            # ``from pkg.mod import f`` where suffix names the module
            if suffix and name and norm.endswith(f"{suffix}/{name}.py"):
                return None
        return None

    def _resolve_name(self, name: str, fn: FunctionInfo, mod: ModuleInfo) -> list[FunctionInfo]:
        scope = fn
        while scope is not None:
            if name in scope.children:
                return [scope.children[name]]
            sibling = scope.parent
            if sibling is not None and name in sibling.children:
                return [sibling.children[name]]
            scope = scope.parent
        if name in mod.functions:
            return [mod.functions[name]]
        if name in mod.imports:
            suffix, orig = mod.imports[name]
            # ``from x import f`` — f may be a function of module x
            target = self._module_function(suffix, orig)
            if target is not None:
                return [target]
            # or f may itself be a module: handled at attribute resolution
        if name in self.classes:
            init = self.mro_methods(name).get("__init__")
            return [init] if init is not None else []
        return []

    def _resolve_method(self, cls_names: Iterable[str], meth: str) -> list[FunctionInfo]:
        out: list[FunctionInfo] = []
        for cls_name in cls_names:
            hit = self.mro_methods(cls_name).get(meth)
            if hit is not None and hit not in out:
                out.append(hit)
        return out

    #: method names too ubiquitous (files, dicts, sockets, arrays all have
    #: them) for name-only fallback resolution to mean anything
    _FALLBACK_BLOCKLIST = frozenset({
        "read", "write", "get", "put", "update", "pop", "add", "close",
        "open", "send", "recv", "start", "stop", "run", "clear", "keys",
        "values", "items", "copy", "append", "extend", "join", "split",
        "encode", "decode", "format", "count", "index", "insert", "remove",
    })

    def _fallback_by_name(self, meth: str) -> list[FunctionInfo]:
        if meth in self._FALLBACK_BLOCKLIST or meth.startswith("__"):
            return []
        cands = self.by_method_name.get(meth, [])
        if 1 <= len(cands) <= FALLBACK_MAX:
            return list(cands)
        return []

    def resolve_callable(self, node: ast.AST, fn: FunctionInfo, mod: ModuleInfo,
                         local_types: dict[str, set[str]]) -> list[FunctionInfo]:
        """Project functions a callable expression may invoke."""
        if isinstance(node, ast.Name):
            return self._resolve_name(node.id, fn, mod)
        if not isinstance(node, ast.Attribute):
            return []
        meth = node.attr
        recv = node.value
        if isinstance(recv, ast.Name):
            if recv.id == "self" and fn.class_name is not None:
                own = self.mro_methods(fn.class_name).get(meth)
                targets = [own] if own is not None else []
                for sub in self._transitive_subclasses(fn.class_name):
                    override = self.classes[sub].methods.get(meth)
                    if override is not None and override not in targets:
                        targets.append(override)
                if targets:
                    return targets
                return self._fallback_by_name(meth)
            if recv.id in mod.imports:     # module alias: health.gate_facades
                suffix, orig = mod.imports[recv.id]
                sub_suffix = f"{suffix}/{orig}" if orig else suffix
                target = (self._module_function(sub_suffix, meth)
                          or self._module_function(suffix, meth))
                if target is not None:
                    return [target]
            types = self._lookup_types(recv.id, fn, local_types)
            if types:
                hits = self._resolve_method(types, meth)
                if hits:
                    return hits
            return self._fallback_by_name(meth)
        if (isinstance(recv, ast.Attribute) and isinstance(recv.value, ast.Name)
                and recv.value.id == "self" and fn.class_name is not None):
            types = self.class_attr_types.get(fn.class_name, {}).get(recv.attr, set())
            hits = self._resolve_method(types, meth)
            if hits:
                return hits
        return self._fallback_by_name(meth)

    def _lookup_types(self, name: str, fn: FunctionInfo,
                      local_types: dict[str, set[str]]) -> set[str]:
        if name in local_types:
            return local_types[name]
        # closure variable: consult enclosing functions' local types
        scope = fn.parent
        while scope is not None:
            parent_types = getattr(scope, "_local_types", None)
            if parent_types and name in parent_types:
                return parent_types[name]
            scope = scope.parent
        return set()

    def _local_types_of(self, fn: FunctionInfo, mod: ModuleInfo) -> dict[str, set[str]]:
        """Flow-insensitive local var -> class-name sets for one body."""
        types: dict[str, set[str]] = {}
        cls_attr = self.class_attr_types.get(fn.class_name or "", {})

        def attr_types(node: ast.AST) -> set[str]:
            if (isinstance(node, ast.Attribute) and isinstance(node.value, ast.Name)
                    and node.value.id == "self"):
                return set(cls_attr.get(node.attr, ()))
            return self.value_types(node, types)

        for stmt in _own_statements(fn):
            if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1:
                t = stmt.targets[0]
                if isinstance(t, ast.Name):
                    got = attr_types(stmt.value)
                    if got:
                        types.setdefault(t.id, set()).update(got)
            elif isinstance(stmt, (ast.For, ast.AsyncFor)):
                if isinstance(stmt.target, ast.Name) and isinstance(
                        stmt.iter, (ast.Tuple, ast.List)):
                    got: set[str] = set()
                    for el in stmt.iter.elts:
                        got |= attr_types(el)
                    if got:
                        types.setdefault(stmt.target.id, set()).update(got)
        fn._local_types = types  # type: ignore[attr-defined]  (closure lookups)
        return types

    def _add_edge(self, caller: FunctionInfo, callee: FunctionInfo, node: ast.AST,
                  kind: str, label: str = "", bound: int = 0) -> None:
        site = CallSite(caller, callee, node, kind, label, bound)
        self.edges.append(site)
        self.edges_by_caller.setdefault(caller.fid, []).append(site)
        self.edges_by_callee.setdefault(callee.fid, []).append(site)
        self.edges_at.setdefault(id(node), []).append(site)

    def _build_edges(self, fn: FunctionInfo, mod: ModuleInfo) -> None:
        local_types = self._local_types_of(fn, mod)
        #: var -> how its future was made (for add_done_callback kinds)
        fut_kind: dict[str, str] = {}
        for stmt in _own_statements(fn):
            if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 and isinstance(
                    stmt.targets[0], ast.Name) and isinstance(stmt.value, ast.Call):
                leaf = last_attr(stmt.value.func) or ""
                if leaf in ("run_in_executor", "create_task", "ensure_future",
                            "create_future"):
                    fut_kind[stmt.targets[0].id] = "loop_cb"
                elif leaf == "submit":
                    fut_kind[stmt.targets[0].id] = "executor"

        def resolve_ref(node: ast.AST) -> list[FunctionInfo]:
            if isinstance(node, (ast.Name, ast.Attribute)):
                return self.resolve_callable(node, fn, mod, local_types)
            return []

        def visit(node: ast.AST, in_await: bool) -> None:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
                return  # nested functions are walked as their own callers
            if isinstance(node, ast.Await):
                visit(node.value, True)
                return
            if isinstance(node, ast.Call):
                self._call_edges(node, fn, mod, local_types, fut_kind,
                                 resolve_ref, in_await)
            for child in ast.iter_child_nodes(node):
                visit(child, False)

        body = getattr(fn.node, "body", [])
        for stmt in body:
            visit(stmt, False)

    def _call_edges(self, call: ast.Call, fn: FunctionInfo, mod: ModuleInfo,
                    local_types, fut_kind, resolve_ref, in_await: bool) -> None:
        leaf = last_attr(call.func) or ""
        dotted = dotted_name(call.func) or leaf

        # deferred-execution special forms seed ownership domains
        if leaf == "partial" and dotted.split(".")[0] in ("functools", "partial"):
            if call.args:
                for target in resolve_ref(call.args[0]):
                    self._add_edge(fn, target, call, "partial",
                                   bound=len(call.args) - 1)
            return
        if leaf == "Thread":
            label = "thread"
            target_node = None
            for kw in call.keywords:
                if kw.arg == "target":
                    target_node = kw.value
                elif kw.arg == "name" and isinstance(kw.value, ast.Constant):
                    label = f"thread:{kw.value.value}"
            for target in resolve_ref(target_node) if target_node is not None else []:
                self._add_edge(fn, target, call, "thread", label=label)
            return
        if leaf == "run_in_executor" and len(call.args) >= 2:
            for target in resolve_ref(call.args[1]):
                self._add_edge(fn, target, call, "executor")
            return
        if leaf == "submit" and call.args:
            for target in resolve_ref(call.args[0]):
                self._add_edge(fn, target, call, "executor")
            return
        if leaf == "run_placed" and call.args:
            # the sharded crypto plane's placement boundary
            # (provider/scheduler.py Shard.run_placed): the callable it is
            # handed executes on a dispatch worker under the shard's
            # placement context — an executor-domain edge, exactly like a
            # pool submission (the cross-thread-state pack must see state
            # the placed callable mutates as worker-owned)
            for target in resolve_ref(call.args[0]):
                self._add_edge(fn, target, call, "executor")
            return
        if leaf == "set_fn" and call.args:
            # lazy-gauge callbacks (obs/metrics.py Gauge.set_fn): evaluated
            # at snapshot/scrape/flight-dump time on WHATEVER thread asks —
            # an executor-domain edge, so state a gauge callback touches
            # (e.g. the autotuner's decision state, provider/autotune.py)
            # counts as cross-thread in the race pack
            for target in resolve_ref(call.args[0]):
                self._add_edge(fn, target, call, "executor")
            return
        if leaf == "on_event" and call.args:
            # fleet event-handler registration (fleet/manager.py
            # GatewayFleet.on_event): handlers fire from the control read
            # loops and the health tick — loop-domain callbacks, exactly
            # like a call_soon registration
            for target in resolve_ref(call.args[0]):
                self._add_edge(fn, target, call, "loop_cb")
            return
        if leaf == "create_subprocess_exec":
            # the fleet's gateway spawn (fleet/manager.py _spawn_member):
            # ``python -m <module> <cfg>`` runs the module's ``main()`` in
            # its OWN process — a "subprocess" ownership edge, so the
            # gateway worker's code is reachable from (and attributed to)
            # the manager that owns its lifecycle
            consts = [a.value for a in call.args
                      if isinstance(a, ast.Constant)
                      and isinstance(a.value, str)]
            for flag, modname in zip(consts, consts[1:]):
                if flag != "-m":
                    continue
                suffix = modname.replace(".", "/") + ".py"
                for path, m in self.modules.items():
                    # path-boundary match: bare endswith would also hit
                    # otherpkg/gateway.py for ``-m pkg.gateway``
                    if ((path == suffix or path.endswith("/" + suffix))
                            and "main" in m.functions):
                        self._add_edge(fn, m.functions["main"], call,
                                       "subprocess")
            return
        if leaf in ("register_message_handler", "register_handler") and len(call.args) >= 2:
            # message-handler registration (net/p2p_node.py
            # register_message_handler): the handler fires from the peer
            # read loop — a loop-domain callback.  Two shapes: a literal
            # verb (``register_handler("x", self._on_x)``), and the
            # messaging.py tuple table, where both arguments are the loop
            # variables of a ``for (msg_type, handler) in ((...), ...)``
            # — resolved here element by element so every table-registered
            # handler gets an edge labelled with its verb (qrproto reuses
            # these as the protocol model's registry handlers, and taint
            # reaches handler bodies only the table names)
            pairs: list[tuple[str, ast.AST]] = []
            verb_node, handler_node = call.args[0], call.args[1]
            if (isinstance(verb_node, ast.Constant)
                    and isinstance(verb_node.value, str)):
                pairs.append((verb_node.value, handler_node))
            elif isinstance(verb_node, ast.Name) and isinstance(handler_node, ast.Name):
                for stmt in _own_statements(fn):
                    if not isinstance(stmt, (ast.For, ast.AsyncFor)):
                        continue
                    t = stmt.target
                    if not (isinstance(t, ast.Tuple) and len(t.elts) == 2
                            and all(isinstance(e, ast.Name) for e in t.elts)
                            and t.elts[0].id == verb_node.id
                            and t.elts[1].id == handler_node.id):
                        continue
                    if isinstance(stmt.iter, (ast.Tuple, ast.List)):
                        for elt in stmt.iter.elts:
                            if (isinstance(elt, ast.Tuple) and len(elt.elts) == 2
                                    and isinstance(elt.elts[0], ast.Constant)
                                    and isinstance(elt.elts[0].value, str)):
                                pairs.append((elt.elts[0].value, elt.elts[1]))
            # the registration call itself still resolves (P2PNode method)
            for target in self.resolve_callable(call.func, fn, mod, local_types):
                self._add_edge(fn, target, call, "await" if in_await else "call")
            for verb, href in pairs:
                for target in resolve_ref(href):
                    self._add_edge(fn, target, href, "loop_cb",
                                   label=f"handler:{verb}")
            return
        if leaf in ("call_soon", "call_later", "call_at", "call_soon_threadsafe"):
            idx = 0 if leaf == "call_soon" or leaf == "call_soon_threadsafe" else 1
            if len(call.args) > idx:
                for target in resolve_ref(call.args[idx]):
                    self._add_edge(fn, target, call, "loop_cb")
            return
        if leaf == "add_done_callback" and call.args:
            recv = call.func.value if isinstance(call.func, ast.Attribute) else None
            kind = "loop_cb"
            if isinstance(recv, ast.Name):
                kind = fut_kind.get(recv.id, "loop_cb")
            for target in resolve_ref(call.args[0]):
                self._add_edge(fn, target, call, kind)
            return
        if leaf in ("create_task", "ensure_future") and call.args:
            inner = call.args[0]
            if isinstance(inner, ast.Call):
                for target in self.resolve_callable(inner.func, fn, mod, local_types):
                    self._add_edge(fn, target, inner, "task")
            else:
                for target in resolve_ref(inner):
                    self._add_edge(fn, target, call, "task")
            return

        # plain (or awaited) call
        for target in self.resolve_callable(call.func, fn, mod, local_types):
            self._add_edge(fn, target, call, "await" if in_await else "call")
        # bare function references passed as arguments (handler tables etc.)
        for arg in [*call.args, *[kw.value for kw in call.keywords]]:
            if isinstance(arg, (ast.Name, ast.Attribute)) and not isinstance(
                    arg, ast.Constant):
                for target in resolve_ref(arg):
                    if target.name == (last_attr(arg) or ""):
                        self._add_edge(fn, target, arg, "ref")


def _own_statements(fn: FunctionInfo):
    """Every statement of ``fn``'s body, excluding nested function bodies."""
    def walk(stmts):
        for stmt in stmts:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            yield stmt
            for field in ("body", "orelse", "finalbody"):
                yield from walk(getattr(stmt, field, []) or [])
            for handler in getattr(stmt, "handlers", []) or []:
                yield from walk(handler.body)
    yield from walk(getattr(fn.node, "body", []))


def _walk_functions(mod: ModuleInfo):
    seen: set[str] = set()

    def rec(fn: FunctionInfo):
        if fn.fid in seen:
            return
        seen.add(fn.fid)
        yield fn
        for child in fn.children.values():
            yield from rec(child)

    for fn in mod.functions.values():
        yield from rec(fn)
    for cls in mod.classes.values():
        for fn in cls.methods.values():
            yield from rec(fn)


def build_callgraph(project: Project) -> CallGraph:
    return CallGraph(project)
