"""JAX-kernel rule pack.

Three failure modes generic linters cannot see in the Pallas/JAX layers
(core/, kem/, sig/):

* ``traced-branch`` — Python ``if``/``while`` on a traced value inside a
  ``@jax.jit`` function: raises TracerBoolConversionError at best, silently
  bakes one branch into the compiled program at worst.  Names derived from
  ``static_argnames`` parameters, module constants, or ``.shape``/``.ndim``/
  ``.dtype`` accesses are compile-time static and fine.
* ``int32-narrowing`` — ``*`` / ``<<`` on kernel tile values: TPU vector
  registers are 32-bit, so a product of two mod-q residues (q=8380417 needs
  23 bits) silently wraps.  Every flagged site must either widen, restructure
  (Horner over limbs, as sig/mldsa_pallas._mm_zeta does), or carry a
  suppression whose comment states the overflow bound.
* ``host-sync`` — ``.item()`` / ``np.asarray`` / ``float()`` on a traced
  value inside a jit function: forces a device→host transfer and a pipeline
  stall on the hot path.

File scoping: traced-branch/host-sync run on any file importing jax;
int32-narrowing runs only on files that use Pallas (where arithmetic runs on
fixed-width vregs and overflow is silent).
"""

from __future__ import annotations

import ast

from .engine import FileContext, Rule, call_name, decorator_names, last_attr

_STATIC_ATTRS = {"shape", "ndim", "dtype", "size"}
#: builtins whose result is host-static when applied to anything
_STATIC_CALLS = {"len", "range", "int", "float", "bool", "min", "max", "isinstance",
                 "getattr", "hasattr", "tuple", "sorted", "abs", "pow", "divmod"}


def _imports_jax(ctx: FileContext) -> bool:
    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.Import):
            if any(a.name == "jax" or a.name.startswith("jax.") for a in node.names):
                return True
        elif isinstance(node, ast.ImportFrom):
            if node.module and (node.module == "jax" or node.module.startswith("jax.")):
                return True
    return False


def _uses_pallas(ctx: FileContext) -> bool:
    return "pallas" in ctx.source


def _is_jit_decorated(func: ast.FunctionDef | ast.AsyncFunctionDef) -> bool:
    names = decorator_names(func)
    return any(n in ("jax.jit", "jit") or n.endswith(".jit") for n in names)


def _static_argnames(func: ast.FunctionDef) -> set[str]:
    """String literals of ``static_argnames=...`` in the jit decorator."""
    out: set[str] = set()
    for dec in func.decorator_list:
        if not isinstance(dec, ast.Call):
            continue
        for kw in dec.keywords:
            if kw.arg in ("static_argnames", "static_argnums"):
                for node in ast.walk(kw.value):
                    if isinstance(node, ast.Constant) and isinstance(node.value, str):
                        out.add(node.value)
    return out


def _param_names(func: ast.FunctionDef) -> list[ast.arg]:
    a = func.args
    return [*a.posonlyargs, *a.args, *a.kwonlyargs,
            *([a.vararg] if a.vararg else []), *([a.kwarg] if a.kwarg else [])]


class _TaintMap:
    """Fixed-point name propagation inside one function body.

    ``tainted`` starts as the traced/tile parameters; an assignment taints
    its targets iff the RHS *references* a tainted name outside of a
    host-static context (``x.shape``, ``len(x)``, ``enumerate`` indices,
    ``range`` loop variables stay host-side).
    """

    def __init__(self, func: ast.FunctionDef, seed: set[str]):
        self.tainted = set(seed)
        body = func.body
        for _ in range(3):  # fixed point for straight-line + simple loops
            before = len(self.tainted)
            for stmt in body:
                self._visit(stmt)
            if len(self.tainted) == before:
                break

    # -- taint tests --------------------------------------------------------

    def is_tainted(self, expr: ast.AST) -> bool:
        """True if ``expr`` references a tainted name outside a static context."""
        return self._first_tainted(expr) is not None

    def _first_tainted(self, expr: ast.AST) -> ast.AST | None:
        if isinstance(expr, ast.Attribute):
            if expr.attr in _STATIC_ATTRS:
                return None  # x.shape is a host int even when x is traced
            return self._first_tainted(expr.value)
        if isinstance(expr, ast.Call):
            fname = call_name(expr)
            if fname and fname.split(".")[-1] in _STATIC_CALLS:
                return None
        if isinstance(expr, ast.Name):
            return expr if expr.id in self.tainted else None
        for child in ast.iter_child_nodes(expr):
            hit = self._first_tainted(child)
            if hit is not None:
                return hit
        return None

    # -- propagation --------------------------------------------------------

    def _targets(self, target: ast.AST) -> list[str]:
        """Names BOUND by an assignment target.  A subscript store taints the
        container, never the index expression (``sh[x + 5*y] = v`` taints
        ``sh``, not ``x``/``y``)."""
        if isinstance(target, ast.Name):
            return [target.id]
        if isinstance(target, (ast.Tuple, ast.List)):
            return [n for e in target.elts for n in self._targets(e)]
        if isinstance(target, ast.Starred):
            return self._targets(target.value)
        if isinstance(target, ast.Subscript):
            return self._targets(target.value)
        return []  # attribute stores don't bind local names

    def _visit(self, node: ast.AST) -> None:
        if isinstance(node, ast.Assign):
            if self.is_tainted(node.value):
                self._assign_targets(node.targets, node.value)
        elif isinstance(node, ast.AugAssign):
            if self.is_tainted(node.value) or self.is_tainted(node.target):
                self.tainted.update(self._targets(node.target))
        elif isinstance(node, ast.AnnAssign) and node.value is not None:
            if self.is_tainted(node.value):
                self.tainted.update(self._targets(node.target))
        elif isinstance(node, (ast.For, ast.AsyncFor)):
            self._loop_target(node.target, node.iter)
        elif isinstance(node, (ast.ListComp, ast.SetComp, ast.GeneratorExp, ast.DictComp)):
            for gen in node.generators:
                self._loop_target(gen.target, gen.iter)
        for child in ast.iter_child_nodes(node):
            self._visit(child)

    def _assign_targets(self, targets: list[ast.AST], value: ast.AST) -> None:
        # `i, c = enumerate(...)` style pairs handled at the loop level; a
        # plain tainted assignment taints every bound name.
        for t in targets:
            self.tainted.update(self._targets(t))

    def _loop_target(self, target: ast.AST, iter_expr: ast.AST) -> None:
        if isinstance(iter_expr, ast.Call):
            fname = (call_name(iter_expr) or "").split(".")[-1]
            if fname == "range":
                return  # range indices are host ints
            if fname == "enumerate" and isinstance(target, ast.Tuple) and len(target.elts) == 2:
                # index is a host int; only the element inherits taint
                if any(self.is_tainted(a) for a in iter_expr.args):
                    self.tainted.update(self._targets(target.elts[1]))
                return
        if self.is_tainted(iter_expr):
            self.tainted.update(self._targets(target))


class TracedBranchRule(Rule):
    id = "traced-branch"
    description = "Python if/while on a traced value inside a @jax.jit function"

    def start_file(self, ctx: FileContext):
        if not _imports_jax(ctx):
            return None
        return {ast.FunctionDef: lambda n: self._check(ctx, n)}

    def _check(self, ctx: FileContext, func: ast.FunctionDef) -> None:
        if not _is_jit_decorated(func):
            return
        static = _static_argnames(func)
        traced = {a.arg for a in _param_names(func) if a.arg not in static}
        taint = _TaintMap(func, traced)
        for node in ast.walk(func):
            if isinstance(node, (ast.If, ast.While)):
                hit = taint._first_tainted(node.test)
                if hit is not None:
                    kind = "if" if isinstance(node, ast.If) else "while"
                    ctx.report(
                        self, node,
                        f"`{kind}` on traced value {last_attr(hit)!r} in jit "
                        f"function {func.name!r}: use jnp.where/lax.cond, or "
                        "mark the argument static",
                    )


class Int32NarrowingRule(Rule):
    id = "int32-narrowing"
    description = (
        "multiply/left-shift on kernel tile values can exceed 31 bits and "
        "silently wrap in int32 vector registers (defers to qrkernel's "
        "interval proofs where they exist)"
    )

    #: functions whose parameters are VMEM tiles: Pallas kernel bodies and
    #: the register-resident helpers they are built from
    _TILE_FUNC_SUFFIXES = ("_kernel", "_tiles")

    def start_file(self, ctx: FileContext):
        if not _uses_pallas(ctx):
            return None
        self._helper_names = self._tile_helper_names(ctx)
        self._proved = self._kernel_proofs(ctx)
        return {ast.FunctionDef: lambda n: self._check(ctx, n)}

    @staticmethod
    def _kernel_proofs(ctx: FileContext) -> dict[int, str]:
        """qrkernel's per-line interval verdicts for this file: sites it
        PROVED in-range (or that carry a `# qrkernel: wrapping` annotation)
        need no suppression comment — the bound is machine-checked, not a
        human claim.  Absent qrkernel (or on its failure), every site is
        flagged exactly as before."""
        try:
            from .kernel.packs import site_status
        except ImportError:  # pragma: no cover - kernel pkg always ships
            return {}
        try:
            return site_status(ctx.path, ctx.source)
        except Exception:  # defensive: a verifier bug must not kill the lint
            return {}

    def _tile_helper_names(self, ctx: FileContext) -> set[str]:
        """Top-level helpers that tile functions call with tile arguments
        (e.g. _rotl/_mm_zeta): their params are tiles too."""
        tile_funcs = set()
        calls_in_tiles: set[str] = set()
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.FunctionDef) and node.name.endswith(self._TILE_FUNC_SUFFIXES):
                tile_funcs.add(node.name)
                for call in ast.walk(node):
                    if isinstance(call, ast.Call):
                        name = call_name(call)
                        if name and "." not in name:
                            calls_in_tiles.add(name)
        # fixed point: helpers called from helpers (absorb_block -> _f1600)
        for _ in range(3):
            grew = False
            for node in ast.walk(ctx.tree):
                if (isinstance(node, ast.FunctionDef)
                        and node.name in calls_in_tiles
                        and node.name not in tile_funcs):
                    tile_funcs.add(node.name)
                    grew = True
                    for call in ast.walk(node):
                        if isinstance(call, ast.Call):
                            name = call_name(call)
                            if name and "." not in name:
                                calls_in_tiles.add(name)
            if not grew:
                break
        return tile_funcs

    def _check(self, ctx: FileContext, func: ast.FunctionDef) -> None:
        if not (func.name.endswith(self._TILE_FUNC_SUFFIXES)
                or func.name in self._helper_names):
            return
        # parameters annotated as host scalars are not tiles
        tile_params = {
            a.arg
            for a in _param_names(func)
            if not (isinstance(a.annotation, ast.Name)
                    and a.annotation.id in ("int", "bool", "float", "str"))
        } - {"self"}
        taint = _TaintMap(func, tile_params)
        seen_lines: set[int] = set()
        for node in ast.walk(func):
            if not (isinstance(node, ast.BinOp)
                    and isinstance(node.op, (ast.Mult, ast.LShift))):
                continue
            if isinstance(node.left, (ast.List, ast.Tuple)) or \
                    isinstance(node.right, (ast.List, ast.Tuple)):
                continue  # sequence replication, not tile arithmetic
            hit = taint._first_tainted(node.left) or taint._first_tainted(node.right)
            if hit is None or node.lineno in seen_lines:
                continue
            if self._proved.get(node.lineno) in ("proved", "wrapping"):
                continue  # machine-checked by qrkernel: no comment needed
            seen_lines.add(node.lineno)
            op = "*" if isinstance(node.op, ast.Mult) else "<<"
            ctx.report(
                self, node,
                f"`{op}` on tile value {last_attr(hit)!r} in {func.name!r}: "
                "prove the 31-bit bound in a suppression comment, or widen/"
                "restructure (Horner over limbs)",
            )


class HostSyncRule(Rule):
    id = "host-sync"
    description = "device->host sync (.item()/np.asarray/float()) inside a jit function"

    _SYNC_CALLS = {"np.asarray", "np.array", "numpy.asarray", "numpy.array",
                   "jax.device_get"}
    _SYNC_METHODS = {"item", "block_until_ready", "tolist"}
    _SYNC_CASTS = {"float", "int", "bool", "complex"}

    def start_file(self, ctx: FileContext):
        if not _imports_jax(ctx):
            return None
        self._stack: list[_TaintMap | None] = []
        return {
            ast.FunctionDef: lambda n: self._enter(n),
            ast.Call: lambda n: self._call(ctx, n),
        }

    def _enter(self, func: ast.FunctionDef) -> None:
        if _is_jit_decorated(func):
            static = _static_argnames(func)
            traced = {a.arg for a in _param_names(func) if a.arg not in static}
            self._taint = _TaintMap(func, traced)
            self._jit_func = func
        elif not getattr(self, "_jit_func", None):
            self._taint = None

    def _call(self, ctx: FileContext, node: ast.Call) -> None:
        func = ctx.enclosing(ast.FunctionDef, ast.AsyncFunctionDef)
        if func is not getattr(self, "_jit_func", None) or self._taint is None:
            return
        name = call_name(node) or ""
        attr = node.func.attr if isinstance(node.func, ast.Attribute) else None
        tainted_arg = any(self._taint.is_tainted(a) for a in node.args)
        if name in self._SYNC_CALLS and tainted_arg:
            ctx.report(self, node,
                       f"{name}() on a traced value forces a device->host sync "
                       "inside a jit function; keep data on device (jnp.asarray)")
        elif attr in self._SYNC_METHODS and isinstance(node.func, ast.Attribute) \
                and self._taint.is_tainted(node.func.value):
            ctx.report(self, node,
                       f".{attr}() on a traced value forces a device->host sync "
                       "inside a jit function")
        elif name in self._SYNC_CASTS and tainted_arg:
            ctx.report(self, node,
                       f"{name}() on a traced value concretizes it on the host "
                       "inside a jit function")


JAX_RULES = (TracedBranchRule, Int32NarrowingRule, HostSyncRule)
