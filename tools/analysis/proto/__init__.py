"""qrproto — cross-process protocol-contract & state-machine verifier.

The fourth analyzer of the qr-analysis ratchet (qrlint → qrflow →
qrkernel → qrproto).  Pure AST on the qrlint engine: extracts the
whole-repo protocol model (send sites, handler registrations, field
reads, negotiated features, per-role state machines) and verifies the
wire contracts over it.  ``python -m tools.analysis.proto.run`` or the
``qrproto`` console script; ``--dump-model`` emits the canonical
verb/field/negotiation table docs/protocol.md pins.
"""

from __future__ import annotations

from ..engine import Rule
from .packs import PROTO_RULES


def proto_rules() -> list[Rule]:
    """Fresh instances of every qrproto rule (the all.py driver and the
    CLI both construct per-run rule objects, mirroring flow/kernel)."""
    return [cls() for cls in PROTO_RULES]
