"""qrproto protocol-model extraction — the whole-repo wire contract as data.

One pass over every parsed file recovers the three protocol surfaces the
wire layer grew across PRs 11-15 (docs/protocol.md):

* **send sites** — ``node.send_message(peer, "<verb>", **fields)`` calls
  (keyword names = frame fields; ``**splat`` arguments are resolved to
  the dict keys assigned in the enclosing function, so the conditional
  ticket fields riding a ``ke_response`` stay visible), and control/
  transport frame constructions: any dict literal carrying a ``"type"``
  key whose value is a dunder string or resolves to a ``fleet/control.py``
  verb constant (``{"type": control.GW_PROBE, "n": n}``) — including
  fields added later by ``frame["k"] = v`` stores in the same function
  (the hello's negotiated-offer keys).
* **handler sites** — ``register_message_handler`` registrations (both
  literal and the messaging.py tuple table, resolved through qrflow's
  call graph — the ``handler:<verb>`` edges callgraph.py records), and
  dispatch comparisons ``mtype == control.X`` / ``hello.get("type") ==
  "__busy__"`` (``!=`` guards count too: the rest of the function is the
  handler body).  Field reads inside a handler follow ``msg["x"]`` /
  ``msg.get("x")`` / ``msg.pop("x")`` and recurse one call deep when the
  message dict is passed on (``self._route_reply(msg)``); any other bare
  use of the dict (``return reply``, ``member.stats = msg``) makes the
  handler a wildcard reader.
* **negotiated features** — hello offer lists (``hello["wire"] =
  ["bin1"]`` stores on the ``__hello__`` frame), their ``QRP2P_*``
  kill-switch env reads (resolved through the gating attribute's default
  chain), and the negotiation-check predicates (functions whose name
  marks them as negotiation guards, closed transitively over calls).

Per-role state machines come from the send→handler graph (entry sends =
sends outside any handler body) plus ``*State.X`` precondition compares
and establishing assignments.  Everything is pure AST — no jax import,
no runtime execution — and deterministic, so ``--dump-model`` output is
byte-stable and docs/protocol.md can pin it.
"""

from __future__ import annotations

import ast
import dataclasses
import re

from ..engine import FileContext, Project, last_attr
from ..flow.callgraph import CallGraph, FunctionInfo, build_callgraph

#: wire/control verbs are dunder-named by convention (fleet/control.py);
#: dispatch-comparison extraction keys on this so app-level string
#: compares never read as protocol dispatch
_DUNDER_VERB_RE = re.compile(r"^__\w+__$")

#: verbs whose handler must have a retry/fallback/giveup edge
REJECT_VERB_RE = re.compile(r"(reject|busy|no_route)")

#: function names that ARE negotiation checks (seed of the guard closure)
GUARD_NAME_RE = re.compile(r"(negotiated|peer_resumption)")

#: kill-switch env vars of negotiated features
_KILL_ENV_RE = re.compile(r"^QRP2P_\w+$")

#: frame envelope fields owned by the transport, not by any verb contract:
#: ``type`` routes the frame, ``_trace`` is the observability context the
#: sender attaches and the dispatcher pops before handlers run
ENVELOPE_FIELDS = frozenset({"type", "_trace"})

#: feature-bound verbs, by hello offer key (declarative, like qrflow's
#: crypto-op models): frames of these verbs may only be sent on paths
#: guarded by that feature's negotiation check.  The binary wire binds no
#: verbs — it changes the envelope, not the message set.
FEATURE_VERBS: dict[str, tuple[str, ...]] = {
    "resume": ("ke_resume", "ke_resume_ok"),
    "wire": (),
}

_REGISTER_NAMES = ("register_message_handler", "register_handler")


@dataclasses.dataclass
class SendSite:
    verb: str
    fields: tuple[str, ...]          # keyword / dict-literal fields
    optional: tuple[str, ...]        # splat- or store-attached fields
    open_fields: bool                # unresolvable ``**splat``: set unknown
    path: str
    line: int
    role: str
    func: str                        # enclosing function qualname ("" = module)
    node: ast.AST
    ctx: FileContext
    handler_verb: str | None = None  # verb of the handler containing this send


@dataclasses.dataclass
class HandlerSite:
    verb: str
    role: str
    path: str
    line: int
    func: str
    reads: tuple[str, ...]
    wildcard: bool                   # handler consumes the dict wholesale
    kind: str                        # "registry" | "dispatch"
    node: ast.AST
    ctx: FileContext
    body: tuple[ast.AST, ...]
    span: tuple[int, int]            # body line span (send→handler edges)
    #: where the handler FUNCTION lives (differs from ctx/node for registry
    #: handlers, whose registration site is the finding anchor)
    def_ctx: FileContext | None = None
    def_node: ast.AST | None = None


@dataclasses.dataclass
class Feature:
    offer_key: str                   # hello key ("wire", "resume")
    tokens: tuple[str, ...]          # offered format names ("bin1", "tik1")
    env: str | None                  # kill-switch env var
    guards: tuple[str, ...]          # seed negotiation-check function names
    verbs: tuple[str, ...]           # feature-bound verbs (FEATURE_VERBS)


@dataclasses.dataclass
class StateRef:
    enum: str
    state: str
    kind: str                        # "require" | "establish"
    path: str
    line: int
    node: ast.AST
    ctx: FileContext
    in_handler: str | None = None


def role_of(path: str) -> str:
    p = path.replace("\\", "/")
    if p.endswith(("fleet/manager.py", "fleet/router.py", "fleet/lease.py")):
        return "router"
    if p.endswith("fleet/gateway.py"):
        return "gateway"
    if p.endswith(("fleet/control.py", "fleet/storm.py", "fleet/stormlib.py")):
        return "client"
    if "/net/" in p or p.startswith("net/"):
        return "transport"
    return "peer"


class ProtocolModel:
    """The extracted protocol surface of one project run."""

    def __init__(self, project: Project):
        self.project = project
        self.cg: CallGraph = build_callgraph(project)
        self.sends: list[SendSite] = []
        self.handlers: list[HandlerSite] = []
        self.features: list[Feature] = []
        self.states: list[StateRef] = []
        #: verb constant NAME -> value ("GW_HELLO" -> "__gw_hello__")
        self.verb_consts: dict[str, str] = {}
        #: module-level str constants per file (offer-token resolution)
        self.str_consts: dict[str, str] = {}
        #: env var -> function names whose body reads it
        self._env_readers: dict[str, set[str]] = {}
        #: hello offer key -> (tokens, gating attr name)
        self._offers: dict[str, tuple[set[str], str | None]] = {}
        #: bare function name -> leaf names of calls inside it
        self._fn_calls: dict[str, set[str]] = {}
        #: leaf name -> bare names of functions calling it
        self._callers: dict[str, set[str]] = {}
        self._fn_by_node: dict[int, FunctionInfo] = {
            id(fn.node): fn for fn in self.cg.functions.values()}

        self._index_constants()
        for ctx in project.contexts.values():
            self._extract_file(ctx)
        self._extract_registry_handlers()
        self._assemble_features()
        self._attach_handler_verbs()
        self.guard_closure = self._guard_closure()

    # -- constants ------------------------------------------------------------

    def _index_constants(self) -> None:
        for ctx in self.project.contexts.values():
            for stmt in ctx.tree.body:
                if not (isinstance(stmt, ast.Assign) and len(stmt.targets) == 1
                        and isinstance(stmt.targets[0], ast.Name)
                        and isinstance(stmt.value, ast.Constant)
                        and isinstance(stmt.value.value, str)):
                    continue
                name, value = stmt.targets[0].id, stmt.value.value
                self.str_consts.setdefault(name, value)
                if _DUNDER_VERB_RE.match(value):
                    self.verb_consts.setdefault(name, value)

    def _verb_of(self, node: ast.AST) -> str | None:
        """Resolve a verb expression: dunder literal or verb constant."""
        if isinstance(node, ast.Constant) and isinstance(node.value, str):
            return node.value if _DUNDER_VERB_RE.match(node.value) else None
        name = last_attr(node)
        if name is not None:
            return self.verb_consts.get(name)
        return None

    # -- per-file extraction --------------------------------------------------

    def _extract_file(self, ctx: FileContext) -> None:
        role = role_of(ctx.path)
        stack: list[ast.AST] = []

        def enclosing_fn() -> FunctionInfo | None:
            for anc in reversed(stack):
                fn = self._fn_by_node.get(id(anc))
                if fn is not None:
                    return fn
            return None

        def visit(node: ast.AST) -> None:
            if isinstance(node, ast.Call):
                self._on_call(ctx, role, node, stack, enclosing_fn())
            elif isinstance(node, ast.Dict):
                self._on_dict(ctx, role, node, stack, enclosing_fn())
            elif isinstance(node, ast.Compare):
                self._on_compare(ctx, role, node, stack, enclosing_fn())
            elif isinstance(node, ast.Assign):
                self._on_assign(ctx, node, stack, enclosing_fn())
            stack.append(node)
            try:
                for child in ast.iter_child_nodes(node):
                    visit(child)
            finally:
                stack.pop()

        visit(ctx.tree)

    # -- calls: send_message sites + env reads --------------------------------

    def _on_call(self, ctx: FileContext, role: str, call: ast.Call,
                 stack: list[ast.AST], fn: FunctionInfo | None) -> None:
        leaf = last_attr(call.func) or ""
        if (leaf == "send_message" and len(call.args) >= 2
                and isinstance(call.args[1], ast.Constant)
                and isinstance(call.args[1].value, str)):
            fields = tuple(sorted(kw.arg for kw in call.keywords
                                  if kw.arg is not None))
            optional: set[str] = set()
            open_fields = False
            for kw in call.keywords:
                if kw.arg is not None:
                    continue
                keys = self._splat_keys(kw.value, fn)
                if keys is None:
                    open_fields = True
                else:
                    optional |= keys
            self.sends.append(SendSite(
                verb=call.args[1].value, fields=fields,
                optional=tuple(sorted(optional)), open_fields=open_fields,
                path=ctx.path, line=call.lineno, role=role,
                func=fn.qualname if fn else "", node=call, ctx=ctx))
            return
        if (isinstance(call.func, ast.Attribute) and leaf == "get"
                and (last_attr(call.func.value) or "").endswith("environ")
                and call.args and isinstance(call.args[0], ast.Constant)
                and isinstance(call.args[0].value, str)
                and _KILL_ENV_RE.match(call.args[0].value)
                and fn is not None):
            self._env_readers.setdefault(call.args[0].value,
                                         set()).add(fn.name)

    def _splat_keys(self, splat: ast.AST, fn: FunctionInfo | None) -> set[str] | None:
        """Dict keys a ``**splat`` argument may contribute, from the
        enclosing function's assignments to it; None = unresolvable."""
        if not isinstance(splat, ast.Name) or fn is None:
            return None
        keys: set[str] = set()
        found = False
        for node in ast.walk(fn.node):
            if (isinstance(node, ast.Assign)
                    and any(isinstance(t, ast.Name) and t.id == splat.id
                            for t in node.targets)
                    and isinstance(node.value, ast.Dict)):
                found = True
                for k in node.value.keys:
                    if isinstance(k, ast.Constant) and isinstance(k.value, str):
                        keys.add(k.value)
            elif (isinstance(node, ast.Assign)
                  and any(isinstance(t, ast.Subscript)
                          and isinstance(t.value, ast.Name)
                          and t.value.id == splat.id
                          and isinstance(t.slice, ast.Constant)
                          and isinstance(t.slice.value, str)
                          for t in node.targets)):
                found = True
                for t in node.targets:
                    if (isinstance(t, ast.Subscript)
                            and isinstance(t.value, ast.Name)
                            and t.value.id == splat.id
                            and isinstance(t.slice, ast.Constant)):
                        keys.add(t.slice.value)
        return keys if found else None

    # -- dict literals: control/transport frame constructions -----------------

    def _on_dict(self, ctx: FileContext, role: str, node: ast.Dict,
                 stack: list[ast.AST], fn: FunctionInfo | None) -> None:
        verb = None
        fields: list[str] = []
        for k, v in zip(node.keys, node.values):
            if not (isinstance(k, ast.Constant) and isinstance(k.value, str)):
                continue
            if k.value == "type":
                verb = self._verb_of(v)
            else:
                fields.append(k.value)
        if verb is None:
            return
        optional: set[str] = set()
        # fields attached after construction: ``frame["k"] = v`` stores on
        # the variable the literal was assigned to (the hello offers)
        var = None
        parent = stack[-1] if stack else None
        if (isinstance(parent, ast.Assign) and len(parent.targets) == 1
                and isinstance(parent.targets[0], ast.Name)
                and parent.value is node):
            var = parent.targets[0].id
        if var is not None and fn is not None:
            for sub in ast.walk(fn.node):
                if (isinstance(sub, ast.Assign)
                        and any(isinstance(t, ast.Subscript)
                                and isinstance(t.value, ast.Name)
                                and t.value.id == var
                                and isinstance(t.slice, ast.Constant)
                                and isinstance(t.slice.value, str)
                                for t in sub.targets)):
                    for t in sub.targets:
                        if not (isinstance(t, ast.Subscript)
                                and isinstance(t.value, ast.Name)
                                and t.value.id == var
                                and isinstance(t.slice, ast.Constant)
                                and isinstance(t.slice.value, str)):
                            continue
                        key = t.slice.value
                        optional.add(key)
                        if verb == "__hello__":
                            self._record_offer(key, sub, fn)
        self.sends.append(SendSite(
            verb=verb, fields=tuple(sorted(fields)),
            optional=tuple(sorted(optional)), open_fields=False,
            path=ctx.path, line=node.lineno, role=role,
            func=fn.qualname if fn else "", node=node, ctx=ctx))

    def _record_offer(self, key: str, assign: ast.Assign,
                      fn: FunctionInfo) -> None:
        """A negotiated-feature offer: ``hello["wire"] = [_BIN_WIRE_NAME]``.
        Tokens resolve through module str constants; the gating attribute
        is the ``self.X`` the enclosing ``if`` tests."""
        tokens: set[str] = set()
        for el in ast.walk(assign.value):
            if isinstance(el, ast.Constant) and isinstance(el.value, str):
                tokens.add(el.value)
            elif isinstance(el, ast.Name) and el.id in self.str_consts:
                tokens.add(self.str_consts[el.id])
        gate = None
        for node in ast.walk(fn.node):
            if isinstance(node, ast.If) and any(
                    sub is assign for sub in ast.walk(node)):
                gate = last_attr(node.test)
        existing = self._offers.get(key)
        if existing:
            existing[0].update(tokens)
            if gate and not existing[1]:
                self._offers[key] = (existing[0], gate)
        else:
            self._offers[key] = (tokens, gate)

    # -- compares: dispatch handler sites + state preconditions ---------------

    def _on_compare(self, ctx: FileContext, role: str, node: ast.Compare,
                    stack: list[ast.AST], fn: FunctionInfo | None) -> None:
        if len(node.ops) != 1 or len(node.comparators) != 1:
            return
        left, right = node.left, node.comparators[0]
        op = node.ops[0]
        state = self._state_chain(right) or self._state_chain(left)
        if state is not None and isinstance(op, (ast.Eq, ast.Is)):
            self.states.append(StateRef(
                enum=state[0], state=state[1], kind="require",
                path=ctx.path, line=node.lineno, node=node, ctx=ctx))
            return
        if not isinstance(op, (ast.Eq, ast.NotEq)):
            return
        verb = self._verb_of(left) or self._verb_of(right)
        if verb is None:
            return
        other = right if self._verb_of(left) else left
        msg_var = self._msg_var_of(other, fn)
        if msg_var is None:
            # not a frame dispatch: the compared expression does not trace
            # back to a message dict's "type" (this is what keeps the
            # ``if __name__ == "__main__"`` idiom out of the model)
            return
        if isinstance(op, ast.Eq):
            body: tuple[ast.AST, ...] = ()
            for anc in reversed(stack):
                if isinstance(anc, ast.If) and any(
                        sub is node for sub in ast.walk(anc.test)):
                    body = tuple(anc.body)
                    break
        else:
            # a ``!= VERB`` guard (raise/return otherwise): the remainder
            # of the enclosing function handles the verb
            body = tuple(fn.node.body) if fn is not None else tuple(ctx.tree.body)
        reads, wildcard = (frozenset(), False)
        if body:
            reads, wildcard = self._collect_reads(body, msg_var, fn)
        if isinstance(op, ast.Eq) and fn is not None:
            # reads the dispatch loop performs BEFORE branching (sender-id
            # cross-checks, trace adoption) apply to every verb dispatched
            # in this function — fold them in, pruning sibling dispatch
            # branches, nested functions, and everything textually after
            # the compare, so one verb's fields never leak onto another's
            def _prune(n: ast.AST) -> bool:
                if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.Lambda)):
                    return True
                if isinstance(n, ast.If) and self._is_dispatch_if(
                        n, msg_var, fn):
                    return True
                return (isinstance(n, ast.stmt)
                        and getattr(n, "lineno", 0) > node.lineno)
            shared, shared_wild = self._collect_reads(
                tuple(fn.node.body), msg_var, fn, prune=_prune)
            reads = frozenset(reads | shared)
            wildcard = wildcard or shared_wild
        lines = [getattr(n, "lineno", node.lineno) for n in body] or [node.lineno]
        ends = [getattr(n, "end_lineno", None) or getattr(n, "lineno", node.lineno)
                for n in body] or [node.lineno]
        self.handlers.append(HandlerSite(
            verb=verb, role=role, path=ctx.path, line=node.lineno,
            func=(fn.qualname if fn else "<module>"),
            reads=tuple(sorted(reads)), wildcard=wildcard, kind="dispatch",
            node=node, ctx=ctx, body=body, span=(min(lines), max(ends))))

    def _state_chain(self, node: ast.AST) -> tuple[str, str] | None:
        """``KeyExchangeState.RESPONDED`` -> ("KeyExchangeState", "RESPONDED")."""
        if not isinstance(node, ast.Attribute):
            return None
        base = last_attr(node.value)
        if base is not None and base.endswith("State"):
            return base, node.attr
        return None

    def _msg_var_of(self, node: ast.AST, fn: FunctionInfo | None) -> str | None:
        """The message-dict variable a ``... == VERB`` compare inspects:
        ``msg.get("type")`` / ``msg["type"]`` directly, or a local assigned
        from one of those in the same function."""
        direct = self._type_read_receiver(node)
        if direct is not None:
            return direct
        if isinstance(node, ast.Name) and fn is not None:
            for sub in ast.walk(fn.node):
                if (isinstance(sub, ast.Assign)
                        and any(isinstance(t, ast.Name) and t.id == node.id
                                for t in sub.targets)):
                    recv = self._type_read_receiver(sub.value)
                    if recv is not None:
                        return recv
        return None

    def _is_dispatch_if(self, node: ast.If, var: str,
                        fn: FunctionInfo | None) -> bool:
        """Is this ``if`` a verb-dispatch branch over ``var``?"""
        for sub in ast.walk(node.test):
            if (isinstance(sub, ast.Compare) and len(sub.ops) == 1
                    and len(sub.comparators) == 1
                    and isinstance(sub.ops[0], (ast.Eq, ast.NotEq))):
                left, right = sub.left, sub.comparators[0]
                if self._verb_of(left) or self._verb_of(right):
                    other = right if self._verb_of(left) else left
                    if self._msg_var_of(other, fn) == var:
                        return True
        return False

    def _type_read_receiver(self, node: ast.AST) -> str | None:
        if (isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute)
                and node.func.attr == "get"
                and isinstance(node.func.value, ast.Name)
                and node.args and isinstance(node.args[0], ast.Constant)
                and node.args[0].value == "type"):
            return node.func.value.id
        if (isinstance(node, ast.Subscript)
                and isinstance(node.value, ast.Name)
                and isinstance(node.slice, ast.Constant)
                and node.slice.value == "type"):
            return node.value.id
        return None

    # -- assignments: state establishment -------------------------------------

    def _on_assign(self, ctx: FileContext, node: ast.Assign,
                   stack: list[ast.AST], fn: FunctionInfo | None) -> None:
        state = self._state_chain(node.value)
        if state is not None:
            self.states.append(StateRef(
                enum=state[0], state=state[1], kind="establish",
                path=ctx.path, line=node.lineno, node=node, ctx=ctx))

    # -- registry handlers (via qrflow callgraph handler edges) ---------------

    def _extract_registry_handlers(self) -> None:
        for edge in self.cg.edges:
            if not edge.label.startswith("handler:"):
                continue
            verb = edge.label.split(":", 1)[1]
            target = edge.callee
            params = [p for p in target.params if p not in ("self", "cls")]
            msg_param = "msg" if "msg" in params else (params[-1] if params else None)
            reads: frozenset[str] = frozenset()
            wildcard = False
            if msg_param is not None:
                reads, wildcard = self._collect_reads(
                    tuple(target.node.body), msg_param, target)
            node = target.node
            self.handlers.append(HandlerSite(
                verb=verb, role=role_of(target.path), path=edge.caller.path,
                line=getattr(edge.node, "lineno", node.lineno),
                func=target.qualname, reads=tuple(sorted(reads)),
                wildcard=wildcard, kind="registry", node=edge.node,
                ctx=edge.caller.ctx, body=tuple(node.body),
                span=(node.lineno, node.end_lineno or node.lineno),
                def_ctx=target.ctx, def_node=node))

    # -- field-read collection ------------------------------------------------

    def _collect_reads(self, body: tuple[ast.AST, ...], var: str,
                       fn: FunctionInfo | None, depth: int = 0,
                       seen: set | None = None,
                       prune=None) -> tuple[frozenset, bool]:
        """(field names read off ``var``, wildcard) for a handler body.

        Follows the dict one call deep when passed on whole (resolved via
        the qrflow call graph); any other bare use is a wildcard read.
        ``prune`` skips whole subtrees (the sibling-dispatch-branch filter).
        """
        if seen is None:
            seen = set()
        reads: set[str] = set()
        wildcard = False
        consumed: set[int] = set()
        nodes: list[ast.AST] = []
        stack_ = list(body)
        while stack_:
            n = stack_.pop()
            if prune is not None and prune(n):
                continue
            nodes.append(n)
            stack_.extend(ast.iter_child_nodes(n))
        for node in nodes:
            if (isinstance(node, ast.Subscript)
                    and isinstance(node.value, ast.Name)
                    and node.value.id == var
                    and isinstance(node.slice, ast.Constant)
                    and isinstance(node.slice.value, str)):
                consumed.add(id(node.value))
                if isinstance(node.ctx, ast.Load):
                    reads.add(node.slice.value)
            elif (isinstance(node, ast.Call)
                  and isinstance(node.func, ast.Attribute)
                  and node.func.attr in ("get", "pop")
                  and isinstance(node.func.value, ast.Name)
                  and node.func.value.id == var
                  and node.args
                  and isinstance(node.args[0], ast.Constant)
                  and isinstance(node.args[0].value, str)):
                consumed.add(id(node.func.value))
                reads.add(node.args[0].value)
            elif isinstance(node, ast.Call):
                # the dict passed on whole: recurse into resolved callees
                positions = [i for i, a in enumerate(node.args)
                             if isinstance(a, ast.Name) and a.id == var]
                keywords = [kw.arg for kw in node.keywords
                            if isinstance(kw.value, ast.Name)
                            and kw.value.id == var and kw.arg]
                if not positions and not keywords:
                    continue
                targets = [e.callee for e in self.cg.edges_at.get(id(node), ())
                           if e.kind in ("call", "await")]
                if not targets or depth >= 3:
                    wildcard = True
                    continue
                resolved_any = False
                for target in targets:
                    offset = 1 if (target.params
                                   and target.params[0] in ("self", "cls")
                                   and target.class_name is not None) else 0
                    names = []
                    for i in positions:
                        if i + offset < len(target.params):
                            names.append(target.params[i + offset])
                    names.extend(k for k in keywords if k in target.params)
                    for pname in names:
                        key = (target.fid, pname)
                        if key in seen:
                            resolved_any = True
                            continue
                        seen.add(key)
                        sub_reads, sub_wild = self._collect_reads(
                            tuple(target.node.body), pname, target,
                            depth + 1, seen)
                        reads |= sub_reads
                        wildcard = wildcard or sub_wild
                        resolved_any = True
                if resolved_any:
                    for a in node.args:
                        if isinstance(a, ast.Name) and a.id == var:
                            consumed.add(id(a))
                    for kw in node.keywords:
                        if isinstance(kw.value, ast.Name) and kw.value.id == var:
                            consumed.add(id(kw.value))
                else:
                    wildcard = True
        for node in nodes:
            if (isinstance(node, ast.Name) and node.id == var
                    and isinstance(node.ctx, ast.Load)
                    and id(node) not in consumed):
                wildcard = True
                break
        return frozenset(reads), wildcard

    # -- features -------------------------------------------------------------

    def _assemble_features(self) -> None:
        # the negotiation predicates are shared plumbing (one `_negotiated`
        # family serves every offer), so every feature lists all seeds
        # rather than guessing a partition
        guard_seeds = tuple(sorted({fn.name for fn in self.cg.functions.values()
                                    if GUARD_NAME_RE.search(fn.name)}))
        for key in sorted(self._offers):
            tokens, gate = self._offers[key]
            self.features.append(Feature(
                offer_key=key, tokens=tuple(sorted(tokens)),
                env=self._env_of_gate(gate), guards=guard_seeds,
                verbs=tuple(FEATURE_VERBS.get(key, ()))))

    def _env_of_gate(self, gate: str | None) -> str | None:
        """Kill-switch env for an offer's gating attribute: the default
        chain ``self.X = default_fn(...) ...`` where ``default_fn`` reads
        ``QRP2P_*``."""
        if gate is None:
            return None
        env_fns = {fname: env for env, fns in self._env_readers.items()
                   for fname in fns}
        for fn in self.cg.functions.values():
            for node in ast.walk(fn.node):
                if not isinstance(node, ast.Assign):
                    continue
                if not any(isinstance(t, ast.Attribute) and t.attr == gate
                           and isinstance(t.value, ast.Name)
                           and t.value.id == "self" for t in node.targets):
                    continue
                for sub in ast.walk(node.value):
                    if isinstance(sub, ast.Call):
                        leaf = last_attr(sub.func) or ""
                        if leaf in env_fns:
                            return env_fns[leaf]
        return None

    # -- send→handler attribution + guard closure -----------------------------

    def _attach_handler_verbs(self) -> None:
        # registry spans are the handler function; dispatch spans are the
        # matched branch (Eq) or whole guard function (NotEq).  Sends and
        # state preconditions attribute to the innermost containing span.
        spans = [(h.path, h.span[0], h.span[1], h.verb) for h in self.handlers]

        def innermost(path: str, line: int) -> str | None:
            best: tuple[int, str] | None = None
            for p, start, end, verb in spans:
                if p == path and start <= line <= end:
                    width = end - start
                    if best is None or width < best[0]:
                        best = (width, verb)
            return best[1] if best else None

        for send in self.sends:
            send.handler_verb = innermost(send.path, send.line)
        for ref in self.states:
            if ref.kind == "require":
                ref.in_handler = innermost(ref.path, ref.line)

    def _guard_closure(self) -> frozenset[str]:
        """Bare names of functions that perform (or transitively call) a
        negotiation check — the guard set proto-unnegotiated-send tests
        membership of.

        Guard status propagates UP (to callers) only through synchronous
        members — predicate wrappers like ``_resume_allowed`` that return
        the check's verdict without acting on it.  An ASYNC member joins
        the closure (the check guards its own sends) but does not confer
        it: the check inside e.g. the app send path guards that path's
        frames, not every caller's — propagating through it would mark
        the whole file guarded and make the rule vacuous."""
        all_sync: dict[str, bool] = {}
        for fn in self.cg.functions.values():
            calls = {last_attr(n.func) or "" for n in ast.walk(fn.node)
                     if isinstance(n, ast.Call)}
            calls.discard("")
            self._fn_calls.setdefault(fn.name, set()).update(calls)
            for leaf in calls:
                self._callers.setdefault(leaf, set()).add(fn.name)
            all_sync[fn.name] = all_sync.get(fn.name, True) and not fn.is_async
        guards = {name for name in self._fn_calls if GUARD_NAME_RE.search(name)}
        for calls in self._fn_calls.values():
            guards |= {leaf for leaf in calls if GUARD_NAME_RE.search(leaf)}
        propagating = set(guards)
        changed = True
        while changed:
            changed = False
            for name, calls in self._fn_calls.items():
                if name in guards or not (calls & propagating):
                    continue
                guards.add(name)
                if all_sync.get(name, False):
                    propagating.add(name)
                changed = True
        return frozenset(guards)

    def is_guarded(self, func_qualname: str) -> bool:
        """True when a send's enclosing function sits on a negotiation-
        guarded path: it (or every transitive caller chain above it)
        contains a negotiation check."""
        bare = func_qualname.split(".")[-1] if func_qualname else ""
        return self._guarded(bare, set())

    def _guarded(self, bare: str, visiting: set[str]) -> bool:
        if not bare or bare in visiting:
            return False
        if bare in self.guard_closure:
            return True
        visiting.add(bare)
        callers = self._callers.get(bare, set()) - {bare}
        if not callers:
            return False
        return all(self._guarded(c, visiting) for c in callers)

    # -- derived views --------------------------------------------------------

    def verbs(self) -> list[str]:
        named = {s.verb for s in self.sends} | {h.verb for h in self.handlers}
        return sorted(named, key=lambda v: (v.startswith("__"), v))

    def sends_of(self, verb: str) -> list[SendSite]:
        return [s for s in self.sends if s.verb == verb]

    def handlers_of(self, verb: str) -> list[HandlerSite]:
        return [h for h in self.handlers if h.verb == verb]

    def feature_of(self, verb: str) -> Feature | None:
        for f in self.features:
            if verb in f.verbs:
                return f
        # features may be absent from a partial run (single-file fixture):
        # fall back to the declarative binding so the rule still applies
        for key, verbs in FEATURE_VERBS.items():
            if verb in verbs:
                return Feature(offer_key=key, tokens=(), env=None,
                               guards=(), verbs=tuple(verbs))
        return None

    def reachable_verbs(self) -> frozenset[str]:
        """Verbs reachable from entry sends over the send→handler graph."""
        entry = {s.verb for s in self.sends if s.handler_verb is None}
        edges: dict[str, set[str]] = {}
        for s in self.sends:
            if s.handler_verb is not None:
                edges.setdefault(s.handler_verb, set()).add(s.verb)
        seen = set(entry)
        frontier = list(entry)
        while frontier:
            v = frontier.pop()
            for nxt in edges.get(v, ()):
                if nxt not in seen:
                    seen.add(nxt)
                    frontier.append(nxt)
        return frozenset(seen)

    def as_dict(self) -> dict:
        """JSON-stable dump of the model (``--dump-model --format json``)."""
        verbs = {}
        for verb in self.verbs():
            sends = self.sends_of(verb)
            handlers = self.handlers_of(verb)
            fields = sorted({f for s in sends for f in s.fields})
            optional = sorted({f for s in sends for f in s.optional}
                              - set(fields))
            feature = self.feature_of(verb)
            verbs[verb] = {
                "fields": fields,
                "optional_fields": optional,
                "senders": sorted({s.role for s in sends}),
                "handlers": sorted({h.func for h in handlers}),
                "handler_roles": sorted({h.role for h in handlers}),
                "reads": sorted({r for h in handlers for r in h.reads}),
                "wildcard_read": any(h.wildcard for h in handlers),
                "feature": feature.offer_key if feature else None,
            }
        return {
            "verbs": verbs,
            "features": [{
                "offer_key": f.offer_key, "tokens": list(f.tokens),
                "env": f.env, "guards": list(f.guards),
                "verbs": list(f.verbs),
            } for f in self.features],
            "states": {
                "required": sorted({f"{s.enum}.{s.state}" for s in self.states
                                    if s.kind == "require"}),
                "established": sorted({f"{s.enum}.{s.state}"
                                       for s in self.states
                                       if s.kind == "establish"}),
            },
        }


def render_model_markdown(model: ProtocolModel) -> str:
    """The canonical verb/field/negotiation table (docs/protocol.md pins
    this byte-for-byte; see tests/test_qrproto.py::test_protocol_md_pin)."""
    d = model.as_dict()
    lines = [
        "| Verb | Flow | Fields | Feature | Handlers |",
        "|---|---|---|---|---|",
    ]
    for verb, info in d["verbs"].items():
        senders = "/".join(info["senders"]) or "?"
        receivers = "/".join(info["handler_roles"]) or "(unhandled)"
        fields = ", ".join(
            [*info["fields"], *[f"{f}?" for f in info["optional_fields"]]]
        ) or "—"
        handlers = ", ".join(f"`{h}`" for h in info["handlers"]) or "—"
        feature = f'`{info["feature"]}`' if info["feature"] else "—"
        lines.append(f"| `{verb}` | {senders} → {receivers} | {fields} "
                     f"| {feature} | {handlers} |")
    lines.append("")
    lines.append("| Feature (hello key) | Tokens | Kill switch | Bound verbs |")
    lines.append("|---|---|---|---|")
    for f in d["features"]:
        tokens = ", ".join(f"`{t}`" for t in f["tokens"]) or "—"
        env = f'`{f["env"]}`' if f["env"] else "—"
        verbs = ", ".join(f"`{v}`" for v in f["verbs"]) or "—"
        lines.append(f'| `{f["offer_key"]}` | {tokens} | {env} | {verbs} |')
    return "\n".join(lines) + "\n"


def extract_model(project: Project) -> ProtocolModel:
    cached = getattr(project, "_qrproto_model", None)
    if cached is None:
        cached = ProtocolModel(project)
        project._qrproto_model = cached  # type: ignore[attr-defined]
    return cached
