"""qrproto contract rules, exposed as qrlint ``Rule`` objects.

One :class:`ProtoAnalysis` is computed per project run (protocol-model
extraction over qrflow's call graph, then contract checks over the
model) and cached on the ``Project``; the thin rule classes below each
publish their own finding id from it, so ``--select``/``--ignore`` and
the inline ``# qrproto: disable=`` suppression machinery work unchanged.

Rule ids:

==========================  ==================================================
proto-unhandled-type        a verb is sent cross-process but no receiving
                            role registers or dispatches a handler for it
proto-dead-handler          a handler is registered for a verb nothing sends
proto-field-mismatch        a handler reads a frame field no send site for
                            that verb supplies, or a send site attaches a
                            field no handler ever reads
proto-unnegotiated-send     a frame bound to a negotiated feature (hello
                            offer + kill switch) is sent on a path with no
                            negotiation check above it
proto-reject-dead-end       a reject/busy/no-route verb's handler has no
                            retry, fallback, or give-up edge — the peer
                            stalls by construction
proto-state-unreachable     a handler precondition (state-enum compare) that
                            no code path establishes, or a handler reachable
                            only through handlers that are themselves
                            unreachable from an entry send
proto-unjustified-suppression  a qrproto suppression with no justification
==========================  ==================================================
"""

from __future__ import annotations

import re

from ..engine import FileContext, Project, Rule, last_attr
from .model import (ENVELOPE_FIELDS, REJECT_VERB_RE, HandlerSite,
                    ProtocolModel, SendSite, extract_model)

import ast

#: handler statements that count as a fallback/giveup edge out of a
#: reject: an explicit control transfer, a call into retry/fail plumbing,
#: or a backoff/shed counter bump (the storm and dial loops' idiom)
_FALLBACK_CALL_RE = re.compile(
    r"(retry|re_?route|re_?connect|fall_?back|give_?up|fail|reject|backoff"
    r"|sleep|shed|abort|close|set_exception|cancel)",
    re.IGNORECASE,
)
_FALLBACK_COUNTER_RE = re.compile(
    r"(busy|reject|fail|retr|fallback|shed|drop|backoff)", re.IGNORECASE)

#: every analyzer prefix the engine accepts — a proto id suppressed via the
#: qrlint/qrkernel spelling must be policed all the same
_SUPPRESS_RE = re.compile(
    r"#\s*(?:qrlint|qrkernel|qrproto|qrlife):\s*disable(?:-file)?\s*=\s*"
    r"(?P<rules>[\w.,\- ]+)(?P<rest>.*)$")


class ProtoAnalysis:
    """All qrproto findings for one project, computed once and cached."""

    def __init__(self, project: Project):
        self.project = project
        self.model: ProtocolModel = extract_model(project)
        self.findings: list[tuple[str, FileContext, object, str]] = []
        self._check_verbs()
        self._check_fields()
        self._check_negotiation()
        self._check_rejects()
        self._check_states()

    @classmethod
    def of(cls, project: Project) -> "ProtoAnalysis":
        cached = getattr(project, "_qrproto_analysis", None)
        if cached is None:
            cached = cls(project)
            project._qrproto_analysis = cached  # type: ignore[attr-defined]
        return cached

    def _add(self, rule_id: str, ctx: FileContext, node, message: str) -> None:
        self.findings.append((rule_id, ctx, node, message))

    # -- verb-level pairing ---------------------------------------------------

    def _check_verbs(self) -> None:
        m = self.model
        for verb in m.verbs():
            sends = sorted(m.sends_of(verb), key=lambda s: (s.path, s.line))
            handlers = sorted(m.handlers_of(verb), key=lambda h: (h.path, h.line))
            if sends and not handlers:
                s = sends[0]
                others = "" if len(sends) == 1 else f" (+{len(sends) - 1} more sites)"
                self._add(
                    "proto-unhandled-type", s.ctx, s.node,
                    f"verb {verb!r} is sent here{others} but no role registers "
                    "or dispatches a handler for it — the frame is dropped on "
                    "the floor by every receiver",
                )
            elif handlers and not sends:
                h = handlers[0]
                self._add(
                    "proto-dead-handler", h.ctx, h.node,
                    f"handler {h.func} is registered for verb {verb!r} but no "
                    "send site in the tree emits that verb",
                )

    # -- field contracts ------------------------------------------------------

    def _check_fields(self) -> None:
        m = self.model
        for verb in m.verbs():
            sends = sorted(m.sends_of(verb), key=lambda s: (s.path, s.line))
            handlers = sorted(m.handlers_of(verb), key=lambda h: (h.path, h.line))
            if not sends or not handlers:
                continue  # the pairing rules own those cases
            reads = {r for h in handlers for r in h.reads} - ENVELOPE_FIELDS
            wildcard = any(h.wildcard for h in handlers)
            sent = ({f for s in sends for f in s.fields}
                    | {f for s in sends for f in s.optional}) - ENVELOPE_FIELDS
            open_fields = any(s.open_fields for s in sends)
            if not wildcard:
                for field in sorted(sent - reads):
                    site = next(s for s in sends
                                if field in s.fields or field in s.optional)
                    self._add(
                        "proto-field-mismatch", site.ctx, site.node,
                        f"field {field!r} of verb {verb!r} is sent but no "
                        f"handler ({', '.join(sorted({h.func for h in handlers}))}) "
                        "ever reads it — dead payload, or a read the model "
                        "cannot see",
                    )
            if not open_fields:
                for field in sorted(reads - sent):
                    h = next(h for h in handlers if field in h.reads)
                    self._add(
                        "proto-field-mismatch",
                        h.def_ctx or h.ctx, h.def_node or h.node,
                        f"handler {h.func} reads field {field!r} of verb "
                        f"{verb!r} but no send site supplies it — the read "
                        "always sees the default",
                    )

    # -- negotiation discipline -----------------------------------------------

    def _check_negotiation(self) -> None:
        m = self.model
        for send in sorted(m.sends, key=lambda s: (s.path, s.line)):
            feature = m.feature_of(send.verb)
            if feature is None:
                continue
            if not m.is_guarded(send.func):
                self._add(
                    "proto-unnegotiated-send", send.ctx, send.node,
                    f"verb {send.verb!r} belongs to negotiated feature "
                    f"{feature.offer_key!r} but is sent from "
                    f"{send.func or '<module>'} with no negotiation check on "
                    "any call path above it — peers that did not offer the "
                    "feature receive a frame they never agreed to",
                )

    # -- reject liveness ------------------------------------------------------

    def _check_rejects(self) -> None:
        m = self.model
        seen: set[tuple[str, str]] = set()
        for h in sorted(m.handlers, key=lambda h: (h.path, h.line)):
            if not REJECT_VERB_RE.search(h.verb):
                continue
            key = (h.verb, h.func)
            if key in seen:
                continue
            seen.add(key)
            if m.sends_of(h.verb) and not self._has_fallback_edge(h.body):
                self._add(
                    "proto-reject-dead-end", h.ctx, h.node,
                    f"handler {h.func} for reject verb {h.verb!r} has no "
                    "retry/fallback/give-up edge (no control transfer, no "
                    "fail/backoff call, no shed counter) — the rejected side "
                    "stalls with the exchange in limbo",
                )

    def _has_fallback_edge(self, body) -> bool:
        for stmt in body:
            for node in ast.walk(stmt):
                if isinstance(node, (ast.Raise, ast.Continue, ast.Break)):
                    return True
                if isinstance(node, ast.Call):
                    leaf = last_attr(node.func) or ""
                    if _FALLBACK_CALL_RE.search(leaf):
                        return True
                if isinstance(node, ast.AugAssign):
                    target = last_attr(node.target) or ""
                    if _FALLBACK_COUNTER_RE.search(target):
                        return True
        return False

    # -- state machine --------------------------------------------------------

    def _check_states(self) -> None:
        m = self.model
        established = {(s.enum, s.state) for s in m.states
                       if s.kind == "establish"}
        seen: set[tuple[str, str]] = set()
        for ref in sorted((s for s in m.states if s.kind == "require"),
                          key=lambda s: (s.path, s.line)):
            if ref.in_handler is None:
                continue  # not a handler precondition
            key = (ref.enum, ref.state)
            if key in established or key in seen:
                continue
            seen.add(key)
            self._add(
                "proto-state-unreachable", ref.ctx, ref.node,
                f"handler for {ref.in_handler!r} requires state "
                f"{ref.enum}.{ref.state}, but no code path ever assigns that "
                "state — the precondition can never hold",
            )
        reachable = m.reachable_verbs()
        flagged: set[str] = set()
        for h in sorted(m.handlers, key=lambda h: (h.path, h.line)):
            if (h.verb in flagged or h.verb in reachable
                    or not m.sends_of(h.verb)):
                continue
            flagged.add(h.verb)
            self._add(
                "proto-state-unreachable", h.ctx, h.node,
                f"handler {h.func} for verb {h.verb!r} is reachable only "
                "through reply chains whose own verbs no entry send ever "
                "triggers — dead protocol state",
            )


class _ProtoRule(Rule):
    """Base: publish one finding id out of the shared analysis."""

    severity = "error"

    def check_project(self, project: Project) -> None:
        analysis = ProtoAnalysis.of(project)
        for rule_id, ctx, node, message in analysis.findings:
            if rule_id == self.id:
                project.report(self, ctx, node, message)


class UnhandledTypeRule(_ProtoRule):
    id = "proto-unhandled-type"
    description = ("a verb is sent cross-process but no receiving role "
                   "registers or dispatches a handler for it")


class DeadHandlerRule(_ProtoRule):
    id = "proto-dead-handler"
    description = "a handler is registered for a verb nothing sends"


class FieldMismatchRule(_ProtoRule):
    id = "proto-field-mismatch"
    description = ("a handler reads a frame field no send site supplies, or "
                   "a sent field no handler ever reads")


class UnnegotiatedSendRule(_ProtoRule):
    id = "proto-unnegotiated-send"
    description = ("a frame bound to a negotiated feature is sent on a path "
                   "with no negotiation check above it")


class RejectDeadEndRule(_ProtoRule):
    id = "proto-reject-dead-end"
    description = ("a reject/busy/no-route handler has no retry, fallback, "
                   "or give-up edge — stall by construction")


class StateUnreachableRule(_ProtoRule):
    id = "proto-state-unreachable"
    description = ("a handler state precondition no send path establishes, "
                   "or a handler unreachable from any entry send")


class ProtoSuppressionRule(Rule):
    """Suppressing a qrproto finding requires a one-line justification after
    the rule ids — the same convention qrflow enforces for its ids."""

    id = "proto-unjustified-suppression"
    severity = "error"
    description = ("a qrproto suppression comment carries no one-line "
                   "justification after the rule id(s)")

    _POLICED: frozenset[str] = frozenset({
        "proto-unhandled-type", "proto-dead-handler", "proto-field-mismatch",
        "proto-unnegotiated-send", "proto-reject-dead-end",
        "proto-state-unreachable", "proto-unjustified-suppression",
    })

    def check_project(self, project: Project) -> None:
        for ctx in project.contexts.values():
            for lineno, line in enumerate(ctx.lines, start=1):
                m = _SUPPRESS_RE.search(line)
                if not m:
                    continue
                blob = m.group("rules")
                rest = m.group("rest") or ""
                sep = re.search(r"[^\w,\- ]", blob)
                ids_part = blob[: sep.start()] if sep else blob
                justification = (blob[sep.start():] if sep else "") + rest
                ids = {tok for part in ids_part.split(",")
                       for tok in part.strip().split() if tok}
                proto_ids = ids & self._POLICED
                if proto_ids and not re.search(r"\w", justification):
                    node = _LineNode(lineno)
                    project.report(
                        self, ctx, node,
                        f"suppression of {', '.join(sorted(proto_ids))} has "
                        "no justification — append one after the rule id "
                        "(e.g. `# qrproto: disable=proto-field-mismatch — "
                        "field consumed by external tooling`)",
                    )


class _LineNode:
    """Minimal AST-node stand-in so line-anchored findings route through
    the normal report/suppression machinery."""

    def __init__(self, lineno: int):
        self.lineno = lineno
        self.end_lineno = lineno
        self.col_offset = 0


PROTO_RULES = (
    UnhandledTypeRule, DeadHandlerRule, FieldMismatchRule,
    UnnegotiatedSendRule, RejectDeadEndRule, StateUnreachableRule,
    ProtoSuppressionRule,
)
