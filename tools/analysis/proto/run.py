"""qrproto CLI — ``python -m tools.analysis.proto.run <package-or-path>``.

Exit status mirrors the qrlint/qrflow/qrkernel ratchet contract: 0 when
the tree is clean (modulo explicit, JUSTIFIED suppressions), 1 when any
error-severity finding remains, 2 on usage errors.  ``--format json``/
``--format sarif`` emit machine-readable output; ``--dump-model`` prints
the extracted protocol model instead of linting — the markdown verb/
field/negotiation table docs/protocol.md commits (drift-pinned by
tests/test_qrproto.py), or the full model as JSON with ``--format json``.
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path

from ..engine import Engine, FileContext, Project, render_findings, resolve_target
from ..flow.sarif import to_sarif
from . import proto_rules
from .model import extract_model, render_model_markdown


def _resolve_target(target: str) -> Path:
    return resolve_target(target, "qrproto")


def _load_project(targets: list[Path]) -> Project:
    files: list[Path] = []
    for t in targets:
        files.extend(sorted(t.rglob("*.py")) if t.is_dir() else [t])
    contexts: dict[str, FileContext] = {}
    for f in files:
        try:
            contexts[str(f)] = FileContext(str(f), f.read_text(encoding="utf-8"))
        except (SyntaxError, UnicodeDecodeError, OSError):
            continue
    return Project(contexts)


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="qrproto",
        description=("cross-process protocol-contract & state-machine "
                     "verifier for the wire layer (docs/static_analysis.md)"),
    )
    ap.add_argument("targets", nargs="*", default=["quantum_resistant_p2p_tpu"],
                    help="files, directories, or package names (default: the package)")
    ap.add_argument("--format", choices=("human", "json", "sarif"),
                    default="human", help="output format (default: human)")
    ap.add_argument("--json", action="store_true",
                    help="alias for --format json (qrlint compatibility)")
    ap.add_argument("--select", help="comma-separated rule ids to run (default: all)")
    ap.add_argument("--ignore", help="comma-separated rule ids to skip")
    ap.add_argument("--list-rules", action="store_true", help="list rules and exit")
    ap.add_argument("--dump-model", action="store_true",
                    help=("print the extracted protocol model (markdown verb "
                          "table; JSON with --format json) and exit"))
    args = ap.parse_args(argv)

    rules = proto_rules()
    if args.list_rules:
        for rule in rules:
            print(f"{rule.id:30} [{rule.severity}] {rule.description}")
        return 0
    if args.select:
        wanted = {r.strip() for r in args.select.split(",")}
        unknown = wanted - {r.id for r in rules}
        if unknown:
            print(f"qrproto: unknown rule id(s): {', '.join(sorted(unknown))}",
                  file=sys.stderr)
            return 2
        rules = [r for r in rules if r.id in wanted]
    if args.ignore:
        dropped = {r.strip() for r in args.ignore.split(",")}
        rules = [r for r in rules if r.id not in dropped]

    targets = [_resolve_target(t) for t in (args.targets or ["quantum_resistant_p2p_tpu"])]
    fmt = "json" if args.json else args.format

    if args.dump_model:
        model = extract_model(_load_project(targets))
        if fmt == "json":
            print(json.dumps(model.as_dict(), indent=2))
        else:
            print(render_model_markdown(model), end="")
        return 0

    engine = Engine(rules)
    findings, suppressed = engine.lint_paths(targets)

    if fmt == "sarif":
        print(json.dumps(to_sarif(findings, suppressed, rules,
                                  tool_name="qrproto"), indent=2))
    else:
        out = render_findings(findings, suppressed, as_json=(fmt == "json"))
        if out and fmt == "human":
            lines = out.splitlines()
            lines[-1] = lines[-1].replace("qrlint:", "qrproto:", 1)
            out = "\n".join(lines)
        if out:
            print(out)
    return 1 if any(f.severity == "error" for f in findings) else 0


if __name__ == "__main__":
    sys.exit(main())
