"""qrlint rule engine: one AST walk per file, visitor dispatch, suppressions.

Design (docs/static_analysis.md):

* A :class:`Rule` registers node handlers per file via ``start_file``; the
  engine does ONE depth-first walk per file and dispatches each node to every
  handler registered for its type — rules never re-walk the tree themselves.
* During the walk ``ctx.stack`` holds the ancestor chain, so handlers can ask
  for the nearest enclosing function/class without parent bookkeeping.
* Cross-file rules implement ``check_project`` and run once after every file
  has been parsed (used by the provider-contract pack).
* Suppression is inline: ``# qrlint: disable=rule-id[,rule-id]`` on the
  flagged line (or any line of the smallest enclosing statement) silences
  exactly those rules there; ``# qrlint: disable-file=rule-id`` at module
  level silences a rule for the whole file.  Suppressions are counted, so a
  run can report how many findings were explicitly waived.
"""

from __future__ import annotations

import ast
import dataclasses
import json
import re
from pathlib import Path
from typing import Any, Callable, Iterable

SEVERITIES = ("error", "warning")

# all comment prefixes share one suppression grammar: `# qrlint: disable=…`
# (qrlint/qrflow ids), `# qrkernel: disable=…` (qrkernel ids), and
# `# qrproto: disable=…` (qrproto ids) — rule ids never collide across the
# analyzers, so a shared parser is unambiguous
_SUPPRESS_RE = re.compile(
    r"#\s*(?:qrlint|qrkernel|qrproto|qrlife):\s*disable(?P<scope>-file)?\s*=\s*(?P<rules>[\w.,\- ]+)")


@dataclasses.dataclass(frozen=True)
class Finding:
    rule: str
    severity: str
    path: str
    line: int
    col: int
    message: str

    def format(self) -> str:
        return f"{self.path}:{self.line}:{self.col}: [{self.rule}] {self.message}"

    def as_dict(self) -> dict[str, Any]:
        return dataclasses.asdict(self)


class Rule:
    """Base class for a lint rule.

    Subclasses set ``id``/``description`` and implement ``start_file`` (for
    per-file AST checks) and/or ``check_project`` (for cross-file checks).
    """

    id: str = ""
    severity: str = "error"
    description: str = ""

    def start_file(self, ctx: "FileContext") -> dict[type, Callable[[ast.AST], None]] | None:
        """Return ``{node_type: handler}`` for this file, or None to skip it."""
        return None

    def finish_file(self, ctx: "FileContext") -> None:
        """Called after the walk of one file (emit deferred findings here)."""

    def check_project(self, project: "Project") -> None:
        """Called once per run with every parsed file (cross-file checks)."""


class FileContext:
    """Parsed source + suppression map + the walk-time ancestor stack."""

    def __init__(self, path: str, source: str):
        self.path = path
        self.source = source
        self.tree = ast.parse(source, filename=path)
        self.lines = source.splitlines()
        #: ancestor chain of the node currently being visited (outermost first)
        self.stack: list[ast.AST] = []
        self.findings: list[Finding] = []
        self.suppressed: list[Finding] = []
        self._line_disables: dict[int, set[str]] = {}
        self._file_disables: set[str] = set()
        #: lazy (start, end) line spans of every statement, for suppression
        #: matching when a finding is reported OUTSIDE the walk (cross-file
        #: and dataflow rules have no ctx.stack to find the enclosing stmt)
        self._stmt_spans: list[tuple[int, int]] | None = None
        for lineno, line in enumerate(self.lines, start=1):
            m = _SUPPRESS_RE.search(line)
            if not m:
                continue
            rules = {r.strip() for r in m.group("rules").split(",") if r.strip()}
            if m.group("scope"):
                self._file_disables |= rules
            else:
                self._line_disables.setdefault(lineno, set()).update(rules)

    # -- scope helpers (valid during the walk) ------------------------------

    def enclosing(self, *types: type) -> ast.AST | None:
        """Innermost ancestor of one of ``types`` (walk-time only)."""
        for node in reversed(self.stack):
            if isinstance(node, types):
                return node
        return None

    def enclosing_function(self) -> ast.AST | None:
        return self.enclosing(ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)

    def enclosing_statement(self, node: ast.AST) -> ast.stmt | None:
        for anc in reversed([*self.stack, node]):
            if isinstance(anc, ast.stmt):
                return anc
        return None

    # -- reporting ----------------------------------------------------------

    def report(self, rule: Rule, node: ast.AST, message: str) -> None:
        finding = Finding(
            rule=rule.id,
            severity=rule.severity,
            path=self.path,
            line=getattr(node, "lineno", 1),
            col=getattr(node, "col_offset", 0) + 1,
            message=message,
        )
        if self._is_suppressed(finding, node):
            self.suppressed.append(finding)
        else:
            self.findings.append(finding)

    def _is_suppressed(self, finding: Finding, node: ast.AST) -> bool:
        if finding.rule in self._file_disables:
            return True
        candidates = set(
            range(getattr(node, "lineno", finding.line),
                  (getattr(node, "end_lineno", None) or finding.line) + 1)
        )
        candidates.add(finding.line)
        stmt = self.enclosing_statement(node)
        if stmt is None:
            # reported outside the walk (cross-file / dataflow rules): find
            # the smallest statement whose span contains the node instead
            span = self._containing_stmt_span(getattr(node, "lineno", finding.line))
            if span is not None:
                candidates.update(range(span[0], span[1] + 1))
        else:
            candidates.update(range(stmt.lineno, (stmt.end_lineno or stmt.lineno) + 1))
        return any(
            finding.rule in self._line_disables.get(line, ()) for line in candidates
        )

    def _containing_stmt_span(self, line: int) -> tuple[int, int] | None:
        """(start, end) of the smallest statement covering ``line``, or None."""
        if self._stmt_spans is None:
            self._stmt_spans = [
                (n.lineno, n.end_lineno or n.lineno)
                for n in ast.walk(self.tree)
                if isinstance(n, ast.stmt) and not isinstance(
                    n, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef))
            ]
        best: tuple[int, int] | None = None
        for start, end in self._stmt_spans:
            if start <= line <= end and (best is None or (end - start) < (best[1] - best[0])):
                best = (start, end)
        return best


class Project:
    """All parsed files of one run, for cross-file rules."""

    def __init__(self, contexts: dict[str, FileContext]):
        self.contexts = contexts
        self.findings: list[Finding] = []
        self.suppressed: list[Finding] = []

    def find_file(self, suffix: str) -> FileContext | None:
        """Locate a file by path suffix (e.g. ``provider/base.py``)."""
        for path, ctx in self.contexts.items():
            if path.replace("\\", "/").endswith(suffix):
                return ctx
        return None

    def report(self, rule: Rule, ctx: FileContext, node: ast.AST, message: str) -> None:
        before = len(ctx.findings)
        ctx.report(rule, node, message)
        if len(ctx.findings) > before:
            self.findings.append(ctx.findings.pop())
        else:
            self.suppressed.append(ctx.suppressed.pop())


class Engine:
    """Runs a rule set over files: parse once, walk once, dispatch handlers."""

    def __init__(self, rules: Iterable[Rule]):
        self.rules = list(rules)

    # -- entry points -------------------------------------------------------

    def lint_source(self, source: str, path: str = "<string>") -> tuple[list[Finding], list[Finding]]:
        """Lint one in-memory module (used by the test fixtures)."""
        ctx = FileContext(path, source)
        self._run_file(ctx)
        project = Project({path: ctx})
        self._run_project(project)
        return (
            ctx.findings + project.findings,
            ctx.suppressed + project.suppressed,
        )

    def lint_paths(self, paths: Iterable[str | Path]) -> tuple[list[Finding], list[Finding]]:
        files: list[Path] = []
        for p in paths:
            p = Path(p)
            if p.is_dir():
                files.extend(sorted(p.rglob("*.py")))
            else:
                files.append(p)
        contexts: dict[str, FileContext] = {}
        findings: list[Finding] = []
        suppressed: list[Finding] = []
        for f in files:
            try:
                ctx = FileContext(str(f), f.read_text(encoding="utf-8"))
            except (SyntaxError, UnicodeDecodeError) as e:
                findings.append(
                    Finding("parse-error", "error", str(f), 1, 1, f"cannot parse: {e}")
                )
                continue
            self._run_file(ctx)
            contexts[str(f)] = ctx
            findings.extend(ctx.findings)
            suppressed.extend(ctx.suppressed)
            ctx.findings = []
            ctx.suppressed = []
        project = Project(contexts)
        self._run_project(project)
        findings.extend(project.findings)
        suppressed.extend(project.suppressed)
        findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
        return findings, suppressed

    # -- internals ----------------------------------------------------------

    def _run_file(self, ctx: FileContext) -> None:
        dispatch: dict[type, list[Callable[[ast.AST], None]]] = {}
        active: list[Rule] = []
        for rule in self.rules:
            handlers = rule.start_file(ctx)
            if handlers is None:
                continue
            active.append(rule)
            for node_type, handler in handlers.items():
                dispatch.setdefault(node_type, []).append(handler)
        if dispatch:
            self._walk(ctx, ctx.tree, dispatch)
        for rule in active:
            rule.finish_file(ctx)

    def _walk(self, ctx: FileContext, node: ast.AST,
              dispatch: dict[type, list[Callable[[ast.AST], None]]]) -> None:
        for handler in dispatch.get(type(node), ()):
            handler(node)
        ctx.stack.append(node)
        try:
            for child in ast.iter_child_nodes(node):
                self._walk(ctx, child, dispatch)
        finally:
            ctx.stack.pop()

    def _run_project(self, project: Project) -> None:
        for rule in self.rules:
            rule.check_project(project)


def resolve_target(target: str, prog: str = "qrlint") -> Path:
    """CLI target resolution shared by every analyzer driver: a path, or a
    dotted/plain package name relative to cwd."""
    p = Path(target)
    if p.exists():
        return p
    p = Path(target.replace(".", "/"))
    if p.exists():
        return p
    raise SystemExit(f"{prog}: no such file, directory, or package: {target!r}")


# -- shared AST helpers used by the rule packs --------------------------------


def dotted_name(node: ast.AST) -> str | None:
    """``a.b.c`` for a Name/Attribute chain, else None."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def call_name(call: ast.Call) -> str | None:
    return dotted_name(call.func)


def last_attr(node: ast.AST) -> str | None:
    """The final identifier of a Name/Attribute chain."""
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return None


def decorator_names(func: ast.FunctionDef | ast.AsyncFunctionDef) -> list[str]:
    """Dotted names of all decorators; for ``functools.partial(f, ...)`` and
    similar calls, the name of the called function AND its first argument."""
    out: list[str] = []
    for dec in func.decorator_list:
        if isinstance(dec, ast.Call):
            name = dotted_name(dec.func)
            if name:
                out.append(name)
            if dec.args:
                inner = dotted_name(dec.args[0])
                if inner:
                    out.append(inner)
        else:
            name = dotted_name(dec)
            if name:
                out.append(name)
    return out


def render_findings(findings: list[Finding], suppressed: list[Finding],
                    as_json: bool = False) -> str:
    if as_json:
        return json.dumps(
            {
                "findings": [f.as_dict() for f in findings],
                "suppressed": [f.as_dict() for f in suppressed],
                "counts": {
                    "error": sum(f.severity == "error" for f in findings),
                    "warning": sum(f.severity == "warning" for f in findings),
                    "suppressed": len(suppressed),
                },
            },
            indent=2,
        )
    lines = [f.format() for f in findings]
    lines.append(
        f"qrlint: {sum(f.severity == 'error' for f in findings)} error(s), "
        f"{sum(f.severity == 'warning' for f in findings)} warning(s), "
        f"{len(suppressed)} suppressed"
    )
    return "\n".join(lines)
