#!/usr/bin/env bash
# Install/entry-point smoke: proves the wheel metadata, console script, and
# import graph are intact without touching a TPU. Run locally or in CI.
set -euo pipefail

python - <<'EOF'
import importlib.metadata as md
import quantum_resistant_p2p_tpu as pkg
ver = md.version("quantum_resistant_p2p_tpu")
assert ver == pkg.__version__, (ver, pkg.__version__)
print(f"import ok: quantum_resistant_p2p_tpu {ver}")
EOF

qrp2p --help >/dev/null
echo "qrp2p --help ok"

python -m quantum_resistant_p2p_tpu --help >/dev/null
echo "python -m quantum_resistant_p2p_tpu --help ok"

# Static-analysis ratchet: the tree must lint clean (docs/static_analysis.md).
python -m tools.analysis.run quantum_resistant_p2p_tpu
echo "qrlint clean"

# Dataflow ratchet: interprocedural secret-taint / constant-time / race
# analysis must also pass (every suppression carries a justification).
python -m tools.analysis.flow.run quantum_resistant_p2p_tpu
echo "qrflow clean"

# Gateway storm smoke (docs/gateway.md): a fast 48-session storm through
# the real TCP transport + protocol engine + autotuner must complete with
# zero failed handshakes (stdlib providers — no accelerator, no OpenSSL).
python -m tools.swarm_bench --storm --peers 48 --concurrency 48 \
    --rekey-every 2 --seed 11 >/dev/null
echo "storm smoke ok (48 sessions, 0 failures)"
