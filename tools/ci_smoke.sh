#!/usr/bin/env bash
# Install/entry-point smoke: proves the wheel metadata, console script, and
# import graph are intact without touching a TPU. Run locally or in CI.
set -euo pipefail

python - <<'EOF'
import importlib.metadata as md
import quantum_resistant_p2p_tpu as pkg
ver = md.version("quantum_resistant_p2p_tpu")
assert ver == pkg.__version__, (ver, pkg.__version__)
print(f"import ok: quantum_resistant_p2p_tpu {ver}")
EOF

qrp2p --help >/dev/null
echo "qrp2p --help ok"

python -m quantum_resistant_p2p_tpu --help >/dev/null
echo "python -m quantum_resistant_p2p_tpu --help ok"

# Static-analysis ratchets (docs/static_analysis.md): the unified driver
# runs qrlint (AST lint) -> qrflow (interprocedural taint/race) -> qrkernel
# (abstract-interpretation kernel verifier) with ONE exit code, and asserts
# the suppression budget (tools/analysis/suppression_budget.json): counts
# per analyzer may only go down — an unbudgeted suppression fails loudly.
python -m tools.analysis.all quantum_resistant_p2p_tpu
echo "qr-analysis clean (qrlint + qrflow + qrkernel, within suppression budget)"

# Gateway storm smoke (docs/gateway.md): a fast 48-session storm through
# the real TCP transport + protocol engine + autotuner must complete with
# zero failed handshakes (stdlib providers — no accelerator, no OpenSSL).
python -m tools.swarm_bench --storm --peers 48 --concurrency 48 \
    --rekey-every 2 --seed 11 >/dev/null
echo "storm smoke ok (48 sessions, 0 failures)"
