#!/usr/bin/env bash
# Install/entry-point smoke: proves the wheel metadata, console script, and
# import graph are intact without touching a TPU. Run locally or in CI.
set -euo pipefail

python - <<'EOF'
import importlib.metadata as md
import quantum_resistant_p2p_tpu as pkg
ver = md.version("quantum_resistant_p2p_tpu")
assert ver == pkg.__version__, (ver, pkg.__version__)
print(f"import ok: quantum_resistant_p2p_tpu {ver}")
EOF

qrp2p --help >/dev/null
echo "qrp2p --help ok"

python -m quantum_resistant_p2p_tpu --help >/dev/null
echo "python -m quantum_resistant_p2p_tpu --help ok"

# Static-analysis ratchets (docs/static_analysis.md): the unified driver
# runs qrlint (AST lint) -> qrflow (interprocedural taint/race) -> qrkernel
# (abstract-interpretation kernel verifier) -> qrproto (protocol-contract
# verifier) -> qrlife (lock-discipline / resource-lifetime / wipe-
# completeness verifier) with ONE exit code, and asserts the suppression
# budget (tools/analysis/suppression_budget.json): counts per analyzer may
# only go down — an unbudgeted suppression fails loudly.
python -m tools.analysis.all quantum_resistant_p2p_tpu
echo "qr-analysis clean (qrlint + qrflow + qrkernel + qrproto + qrlife, within suppression budget)"

# The protocol model must still extract (send/handler/feature tables for
# docs/protocol.md) — a refactor that breaks extraction would silently
# blind the contract checks, so probe the dump path explicitly.
python -m tools.analysis.proto.run quantum_resistant_p2p_tpu --dump-model >/dev/null
echo "qrproto --dump-model ok"

# The lock-order graph must still extract (the deadlock check is only as
# good as the edges it sees) — probe the dump path and require the known
# scheduler->instrument edge to be present.
python -m tools.analysis.life.run quantum_resistant_p2p_tpu --dump-lock-graph \
    | grep -q "DeviceProgramScheduler._lock" \
    || { echo "qrlife --dump-lock-graph lost the scheduler lock edges" >&2; exit 1; }
echo "qrlife --dump-lock-graph ok"

# Gateway storm smoke (docs/gateway.md): a fast 48-session storm through
# the real TCP transport + protocol engine + autotuner must complete with
# zero failed handshakes (stdlib providers — no accelerator, no OpenSSL).
python -m tools.swarm_bench --storm --peers 48 --concurrency 48 \
    --rekey-every 2 --seed 11 >/dev/null
echo "storm smoke ok (48 sessions, 0 failures)"

# Data-plane smoke (docs/gateway.md "Bulk-heavy storms"): a small
# bulk-mix storm through the batched device AEAD + binary wire must
# complete with zero failures (speedup/latency gates are full-size-run
# territory — bench.py --storm --bulk-mix; sessions < 48 run in smoke
# mode, failures-only, no committed artifact).
python bench.py --storm --bulk-mix --sessions 16 >/dev/null
echo "bulk-mix smoke ok (batched AEAD + binary wire, 0 failures)"

# Fleet chaos smoke (docs/fleet.md): 3 gateway PROCESSES behind the
# consistent-hash router, 60 sessions, one seeded mid-storm SIGKILL of
# gw1 — must converge with 0 lost established sessions, 0 plaintext
# sends, a fired kill, and a bounded handshake-failure burst.  Small
# session counts run in smoke mode: no committed-artifact writes.
python bench.py --storm --fleet 3 --sessions 60 >/dev/null
echo "fleet chaos smoke ok (3 gateways, 60 sessions, seeded gw1 kill survived)"

# Resumption smoke (docs/protocol.md "Session resumption"): every session
# drops its TCP connection mid-workload and must re-establish via its
# ticket — gated on 0 failures, a >=90% resume rate, resume-p50 under the
# full handshake's, and ~0 device trips across the sequential cost probe.
python bench.py --storm --resume-mix --sessions 24 >/dev/null
echo "resume-mix smoke ok (1-RTT ticket resumes, 0 failures)"

# Drain / rolling-restart smoke (docs/robustness.md "Rolling restarts"):
# a 2-gateway PROCESS fleet, every gateway drained (SIGTERM-style) and
# respawned mid-storm — 0 lost established sessions and at least one
# displaced session resuming VIA TICKET on wherever the ring re-routed it.
python bench.py --storm --fleet 2 --roll --sessions 40 >/dev/null
echo "drain smoke ok (rolling restart survived: 0 lost sessions, >=1 ticket resume)"

# HA control-plane smoke (docs/fleet.md "HA control plane"): 2 router
# replicas, 2 gateway processes, a seeded mid-storm SIGKILL of the
# leader plus a rolling restart of every router — 0 lost established
# sessions, clients failing over across the router ring, and at least
# one post-failover reconnect resuming via a ticket minted under the
# dead leader's STEK (the replicated accept window really survived).
python bench.py --storm --fleet 2 --router-roll --routers 2 --sessions 40 >/dev/null
echo "router-roll smoke ok (leader SIGKILL + router roll survived: 0 lost sessions, post-failover ticket resume)"

# Committed-artifact size cap: metrics snapshots are DIGESTS by default
# (tools/swarm_bench.py snapshot_digest — a storm's raw dump is one
# registry per session, ~240k lines); a snapshot over 256 KiB means some
# path regressed to the raw dump without --full-snapshots.
for f in bench_results/*_metrics_snapshot.json; do
    [ -e "$f" ] || continue
    size=$(wc -c < "$f")
    if [ "$size" -gt 262144 ]; then
        echo "committed metrics snapshot too big: $f (${size} bytes > 256 KiB) — digest mode regressed?" >&2
        exit 1
    fi
done
echo "metrics-snapshot size cap ok (digests only)"

# FrodoKEM device-path smoke (docs/dispatch_budget.md "Kernel matrix"):
# a 2-batch keygen/encaps/decaps roundtrip through the tpu-backend
# provider must match the pure-Python reference byte-for-byte AND the
# pinned health KAT must pass — a minimal image whose Frodo kernel path
# silently regressed to an inconsistent fallback fails here, before any
# bench ever reports its numbers.
python - <<'EOF'
import numpy as np

from quantum_resistant_p2p_tpu.provider import health
from quantum_resistant_p2p_tpu.provider.kem_providers import FrodoKEMKeyExchange
from quantum_resistant_p2p_tpu.pyref import frodo_ref

kem = FrodoKEMKeyExchange(security_level=1, backend="tpu", use_aes=False)
verdict = health._check_frodo_kat(kem)
assert verdict.ok, verdict.detail

p = frodo_ref.PARAMS[kem.name]
pks, sks = kem.generate_keypair_batch(2)
cts, sss = kem.encapsulate_batch(pks)
got = kem.decapsulate_batch(sks, cts)
sss, cts, sks = (np.asarray(a) for a in (sss, cts, sks))
assert np.array_equal(np.asarray(got), sss), "decaps != encaps ss"
for i in range(2):
    ref_ss = frodo_ref.decaps(p, bytes(sks[i]), bytes(cts[i]))
    assert bytes(sss[i]) == ref_ss, f"lane {i}: device ss != pyref"
print("frodo device KAT smoke ok (2-batch roundtrip, pyref-pinned)")
EOF

# Telemetry scrape smoke (docs/observability.md "Live endpoints"): an
# engine with telemetry_port=0 (ephemeral) must serve /healthz and a
# Prometheus /metrics exposing the cost ledger's padding-waste gauge and
# a compile counter; the fleet variant asserts every gateway heartbeat
# carries its own telemetry port so qrtop can find the scrapes.
python - <<'EOF'
import asyncio, urllib.request

from quantum_resistant_p2p_tpu.fleet.stormlib import (StormAEAD,
                                                      register_storm_providers)

register_storm_providers()
from quantum_resistant_p2p_tpu.app.messaging import SecureMessaging
from quantum_resistant_p2p_tpu.net.p2p_node import P2PNode
from quantum_resistant_p2p_tpu.provider import get_kem, get_signature


async def main():
    node = P2PNode(node_id="scrape-smoke", host="127.0.0.1", port=0)
    await node.start()
    eng = SecureMessaging(node, kem=get_kem("STORM-KEM", "tpu"),
                          symmetric=StormAEAD(),
                          signature=get_signature("STORM-SIG", "tpu"),
                          use_batching=True, telemetry_port=0)
    await eng.wait_ready()
    await eng._kem_keygen()  # one real flush so the ledger has occupancy
    port = eng.telemetry_port
    assert port, "telemetry_port=0 must bind an ephemeral port"

    def get(path):
        with urllib.request.urlopen(f"http://127.0.0.1:{port}{path}",
                                    timeout=5) as r:
            return r.status, r.read().decode()

    status, _ = get("/healthz")
    assert status == 200, status
    status, prom = get("/metrics")
    assert status == 200, status
    assert "qrp2p_padding_waste_fraction" in prom, "cost gauge missing"
    assert "qrp2p_cost_compile_events_total" in prom, "compile counter missing"
    eng.stop_telemetry()
    await node.stop()

    # fleet variant: every registered gateway announces its telemetry port
    from quantum_resistant_p2p_tpu.fleet.manager import GatewayFleet
    fleet = GatewayFleet(2, spawn="task", hb_interval=0.1, telemetry_port=0)
    try:
        await fleet.start()
        await asyncio.sleep(0.5)  # a heartbeat round
        for m in fleet.members.values():
            assert m.telemetry_port, f"{m.gateway_id}: no telemetry port"
            assert m.stats.get("telemetry_port") == m.telemetry_port, \
                f"{m.gateway_id}: heartbeat does not carry the port"
        with urllib.request.urlopen(
                f"http://127.0.0.1:{fleet.telemetry.port}/fleet",
                timeout=5) as r:
            assert r.status == 200, r.status
    finally:
        await fleet.stop()

asyncio.run(main())
print("telemetry scrape smoke ok (cost gauges live; fleet heartbeats carry ports)")
EOF

# Fleet-observability smoke (docs/observability.md): two processes' span
# dumps — the child's recv chain parented on the parent's propagated wire
# context — must merge into ONE chrome trace with two process lanes, one
# shared trace id, and a cross-node flow edge; and the SLO engine must
# fire a deterministic fast-burn alert on an injected-clock timeline.
python - <<'EOF'
import json, tempfile
from pathlib import Path

from quantum_resistant_p2p_tpu.obs import slo as obs_slo
from quantum_resistant_p2p_tpu.obs import trace as obs_trace
from tools import trace_merge

tmp = Path(tempfile.mkdtemp(prefix="qrp2p_obs_smoke_"))
# node A: a send whose context "rides the wire"
ta = obs_trace.Tracer(tag="aaaa")
with obs_trace.node_scope("alice"), ta.span("net.send", msg_type="ke_init"):
    wire = {"trace_id": obs_trace.current().trace_id,
            "span_id": obs_trace.current().span_id}
a_dump = obs_trace.span_dump(node="alice", tracer=ta)
# node B: adopts the wire context, as net/p2p_node.py does on recv
tb = obs_trace.Tracer(tag="bbbb")
parent = obs_trace.adopt_wire_context(wire)
assert parent is not None
with tb.span("net.recv", parent=parent, msg_type="ke_init"):
    with tb.span("handshake.respond"):
        pass
b_dump = obs_trace.span_dump(node="bob", tracer=tb)
(tmp / "a.json").write_text(json.dumps(a_dump))
(tmp / "b.json").write_text(json.dumps(b_dump))
doc = trace_merge.merge_files([tmp / "a.json", tmp / "b.json"])
assert doc["otherData"]["merged_nodes"] == ["alice", "bob"], doc["otherData"]
assert doc["otherData"]["cross_node_edges"] == 1, doc["otherData"]
tids = {e["args"]["trace_id"] for e in doc["traceEvents"] if e["ph"] == "X"}
assert len(tids) == 1, tids  # one causal chain across both processes

# SLO eval: 100% failures for 2 minutes must alert on the fast window
clock = iter(range(0, 10_000, 60)).__next__
bad = {"n": 0.0}
eng = obs_slo.SLOEngine(clock=lambda: float(clock()))
eng.add(obs_slo.SLOSpec("smoke", objective=0.9,
                        probe=lambda: (0.0, bad["n"]),
                        fast_burn=5.0, slow_burn=2.0))
for _ in range(3):
    bad["n"] += 100.0
    report = eng.status()
assert report["alerting"] == ["smoke"], report
print("trace-merge + SLO-eval smoke ok")
EOF
